"""Device task-tracer decoding: the megakernel's trace ring → records,
chrome-trace rows, and measured overlap metrics.

The device half lives in ``megakernel/`` (``MegaDims.trace`` adds an
SMEM ring output; every grid iteration records its task's
``(task_id, opcode, layer, slot, begin, end[, mid])`` — see
``megakernel/task.py`` for the field layout and
``megakernel/kernels.py::trace_tick`` for the clock). This module is
the host half:

- :func:`decode_trace` — the raw ``[tp, NS, T, TRACE_INTS]`` int32
  array → flat :class:`TaskRecord` list.
- :func:`validate_ring` — gap-free + clock-monotonic + dependency-order
  checks (``begin[consumer] >= end[producer]`` for every scoreboard
  edge of the scheduled order) — the decoder-side analog of the
  scheduler's ``_validate``.
- :func:`overlap_report` — the MEASURED overlap exposure: for every
  AR_SEND/AR_WAIT pair (and fused ALLREDUCE comm phase) the comm
  window, how much of it coincided with compute work (the hidden part:
  the tile-0 prefetch AR_WAIT fires before blocking, plus any whole
  task scheduled inside the window), and what remained exposed.
  Replaces the analytic ``overlap_exposure_estimate`` arm of
  ``perf/MEGA_SERVE.json`` with ring-derived numbers
  (``perf/MEGA_TRACE.json``).
- :func:`records_to_chrome` / :func:`merge_with_host_profile` — device
  task rows merged into the SAME one-file timeline
  ``runtime/profiling.py`` builds (host ``trace_span``s + device
  tasks, pid-namespaced per rank), tagged with the launch's request
  trace ids so one request can be followed server → router → replica →
  engine → individual device tasks.
- :func:`observe_launch` — feeds ``tdt_mega_task_seconds{opcode}``
  histograms and the ``tdt_mega_overlap_exposure`` gauge in the PR 5
  registry from one launch's ring.

Clock semantics (docs/profiling.md "Device task tracer"): on hardware
whose Pallas exposes a cycle counter the ticks are cycles; everywhere
else — always under ``interpret=True`` — they are the kernel's logical
clock (one tick per instrumentation point). Tick durations are scaled
to seconds by apportioning the launch's measured host wall time over
rank 0's total ticks, so histogram units are honest on both clocks;
the *structure* (which phases coincide, dependency order) is
clock-exact either way.
"""

from __future__ import annotations

import dataclasses
import gzip
import json
import os

import numpy as np

from triton_distributed_tpu.megakernel.task import (
    COMM_TASKS,
    TR_BEGIN,
    TR_END,
    TR_FLAG,
    TR_LAYER,
    TR_MID,
    TR_OPCODE,
    TR_SLOT,
    TR_TASK_ID,
    TRACE_INTS,
    TaskType,
)
from triton_distributed_tpu.obs import metrics as obs_metrics

# Device-task rows sit in their own pid INSIDE each rank's pid
# namespace: rank r's host events live at ``r * _PID_STRIDE + pid``
# (runtime/profiling.py), and DEVICE_TASK_PID < _PID_STRIDE keeps the
# device rows inside rank r's block, never colliding with another
# rank's.
DEVICE_TASK_PID = 9_000_000


class TraceError(ValueError):
    """A decoded ring violated a structural invariant."""


# Hot-path lookup tables: TaskRecord.op / .is_comm run per record per
# traced launch inline on the serving decode path; constructing a
# TaskType enum per call was the decode cost's second-largest term.
_OP_NAMES = {int(t): t.name for t in TaskType}
_COMM_OPS = frozenset(int(t) for t in COMM_TASKS)
_AR_SEND = int(TaskType.AR_SEND)
_AR_WAIT = int(TaskType.AR_WAIT)
_ALLREDUCE = int(TaskType.ALLREDUCE)
_A2A_SEND = int(TaskType.A2A_SEND)
_A2A_WAIT = int(TaskType.A2A_WAIT)
_RING_POLL = int(TaskType.RING_POLL)


class TaskRecord:
    """One decoded (rank, step, task) ring record.

    A ``__slots__`` class with a positional ctor, not a dataclass:
    decoding runs INLINE on the serving decode path (every traced
    launch), and frozen-dataclass field assignment was the decode
    cost's dominant term — the record count is O(tasks · steps ·
    ranks) per launch and the tracer-overhead bar
    (perf/MEGA_TRACE.json) budgets this.
    """

    __slots__ = ("rank", "step", "index", "task_id", "opcode", "layer",
                 "slot", "begin", "end", "mid")

    def __init__(self, rank, step, index, task_id, opcode, layer, slot,
                 begin, end, mid):
        self.rank = rank
        self.step = step
        self.index = index      # position in the scheduled order (grid t)
        self.task_id = task_id  # builder id (header slot 4)
        self.opcode = opcode    # TaskType value
        self.layer = layer
        self.slot = slot        # header arg0 (e.g. allreduce parity slot)
        self.begin = begin
        self.end = end
        self.mid = mid          # 0 = no intra-task phase stamp

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"TaskRecord(rank={self.rank}, step={self.step}, "
                f"t={self.index}, {self.op}, [{self.begin}, {self.end}])")

    @property
    def op(self) -> str:
        name = _OP_NAMES.get(self.opcode)
        return name if name is not None else f"OP{self.opcode}"

    @property
    def dur(self) -> int:
        return self.end - self.begin

    @property
    def is_comm(self) -> bool:
        return self.opcode in _COMM_OPS


def _as_ranked(trace) -> np.ndarray:
    """Normalize a ring array to ``[tp, NS, T, TRACE_INTS]``."""
    arr = np.asarray(trace)
    if arr.ndim == 3:
        arr = arr[None]
    if arr.ndim != 4 or arr.shape[-1] != TRACE_INTS:
        raise TraceError(
            f"expected [tp, NS, T, {TRACE_INTS}] ring, got {arr.shape}"
        )
    return arr


def decode_trace(trace, strict: bool = True) -> list[TaskRecord]:
    """Decode a device ring into records. ``strict=True`` (the
    megakernel contract) raises :class:`TraceError` on an unwritten
    row — that ring is dense by construction (one record per grid
    iteration), so a zero flag means the kernel never reached that
    iteration and the trace is not evidence of anything.
    ``strict=False`` skips unwritten rows instead: sparse rings (the
    standalone gemm_ar kernel's per-phase rows — not every grid
    position owns every phase) decode through the same path."""
    arr = _as_ranked(trace)
    records: list[TaskRecord] = []
    n_ranks, nsteps, T, _ = arr.shape
    # ONE C-level conversion to native ints (tolist) instead of eight
    # numpy-scalar casts per record: decoding runs inline on the
    # serving decode path (every traced launch), so its cost is part
    # of the tracer overhead perf/MEGA_TRACE.json budgets.
    nested = arr.tolist()
    for r in range(n_ranks):
        for s in range(nsteps):
            rows = nested[r][s]
            for t in range(T):
                row = rows[t]
                if row[TR_FLAG] != 1:
                    if not strict:
                        continue
                    raise TraceError(
                        f"unwritten ring record at rank={r} step={s} "
                        f"task={t} (flag={row[TR_FLAG]}): the "
                        "trace has gaps"
                    )
                records.append(TaskRecord(
                    r, s, t, row[TR_TASK_ID], row[TR_OPCODE],
                    row[TR_LAYER], row[TR_SLOT], row[TR_BEGIN],
                    row[TR_END], row[TR_MID],
                ))
    return records


def validate_ring(
    records: list[TaskRecord], order=None, doorbell: int | None = None,
) -> list[str]:
    """Structural checks over decoded records; returns violation
    strings (empty == consistent).

    - every record's clock interval is well-formed (``begin < end``,
      ``mid`` inside it when stamped — EXCEPT RING_POLL records, whose
      mid column carries the observed work-ring doorbell, not a clock
      tick);
    - per (rank, step) the launch order is clock-monotonic (the grid is
      sequential: record i+1 must begin at/after record i ended);
    - with ``order`` (the scheduled ``list[Task]``), every scoreboard
      edge holds on the clock: ``begin[consumer] >= end[producer]``
      within a step, and step s+1's records all begin after step s's
      last end (the cross-step dependency the multi-step band implies);
    - with ``doorbell`` (the value ``WorkRing.publish`` returned for
      this launch), every RING_POLL record must have stamped exactly
      it — a mismatch means a round ran against a ring snapshot the
      host did not publish for it (the doorbell-gap check; the resident
      loop's proof that no round consumed stale ring state).
    """
    problems: list[str] = []
    by_rs: dict[tuple, list[TaskRecord]] = {}
    for rec in records:
        by_rs.setdefault((rec.rank, rec.step), []).append(rec)
    for (rank, step), recs in sorted(by_rs.items()):
        recs = sorted(recs, key=lambda x: x.index)
        for rec in recs:
            if rec.begin >= rec.end:
                problems.append(
                    f"rank{rank} step{step} t{rec.index} {rec.op}: "
                    f"begin {rec.begin} >= end {rec.end}"
                )
            if rec.opcode == _RING_POLL:
                if doorbell is not None and rec.mid != doorbell:
                    problems.append(
                        f"rank{rank} step{step} t{rec.index} RING_POLL: "
                        f"observed doorbell {rec.mid} != published "
                        f"{doorbell} (stale ring snapshot)"
                    )
            elif rec.mid and not (rec.begin <= rec.mid <= rec.end):
                problems.append(
                    f"rank{rank} step{step} t{rec.index} {rec.op}: mid "
                    f"{rec.mid} outside [{rec.begin}, {rec.end}]"
                )
        for a, b in zip(recs, recs[1:]):
            if b.begin < a.end:
                problems.append(
                    f"rank{rank} step{step}: t{b.index} {b.op} began at "
                    f"{b.begin} before t{a.index} {a.op} ended at {a.end}"
                )
        if order is not None:
            by_id = {rec.task_id: rec for rec in recs}
            for task in order:
                rec = by_id.get(task.task_id)
                if rec is None:
                    problems.append(
                        f"rank{rank} step{step}: scheduled task "
                        f"{task.task_id} has no ring record"
                    )
                    continue
                for dep in task.deps:
                    prod = by_id.get(dep.producer)
                    if prod is not None and rec.begin < prod.end:
                        problems.append(
                            f"rank{rank} step{step}: consumer "
                            f"{task.task_id} ({rec.op}) began at "
                            f"{rec.begin} before producer "
                            f"{dep.producer} ended at {prod.end}"
                        )
    # Cross-step ordering per rank.
    by_rank_step: dict[int, dict[int, list[TaskRecord]]] = {}
    for rec in records:
        by_rank_step.setdefault(rec.rank, {}).setdefault(
            rec.step, []).append(rec)
    for rank, steps in sorted(by_rank_step.items()):
        keys = sorted(steps)
        for s0, s1 in zip(keys, keys[1:]):
            hi = max(r.end for r in steps[s0])
            lo = min(r.begin for r in steps[s1])
            if lo < hi:
                problems.append(
                    f"rank{rank}: step {s1} began at {lo} before step "
                    f"{s0} ended at {hi}"
                )
    return problems


def overlap_report(records: list[TaskRecord]) -> dict:
    """MEASURED overlap exposure from the ring.

    Per (rank, step), each comm window is an AR_SEND..AR_WAIT pair
    (``MegaConfig.overlap_ar``: the window opens when the send's
    puts are in flight — its ``mid`` — and closes when the wait's
    blocked phase ends), a fused ALLREDUCE's ``[begin, mid]`` comm
    phase, or — MoE graphs — an A2A_SEND..A2A_WAIT EP-combine window
    (ONE window per gate layer: it opens at the FIRST phase's ``mid``,
    so the second half of the expert grouped GEMMs is exactly the work
    it hides under). Hidden = the part of the window coinciding with
    compute work: whole tasks scheduled inside it plus the wait's
    pre-block phase (tile-0 prefetch + dispatch — ``[begin, mid]`` of
    the wait). Exposed = the blocked remainder (``[mid, end]`` of the
    wait; the whole comm phase of a fused exchange).
    ``hidden_fraction`` aggregates every window; the ``a2a_*`` keys
    break the A2A family out (what perf/MOE_SERVE.json reports). The
    ``ring_*`` keys summarize RING_POLL records (resident decode):
    poll count and the doorbell range they observed — a resident
    session's launches should show doorbells climbing 1, 2, 3, … with
    no repeats within a launch.
    """
    windows = 0
    comm = hidden = exposed = 0
    a2a_windows = 0
    a2a_comm = a2a_hidden = a2a_exposed = 0
    ring_polls = 0
    ring_doorbells: set[int] = set()
    by_rs: dict[tuple, list[TaskRecord]] = {}
    for rec in records:
        by_rs.setdefault((rec.rank, rec.step), []).append(rec)

    def _window(recs, open_t, close_t, wait):
        """(comm, hidden, exposed) of one send..wait window."""
        c = close_t - open_t
        h = (wait.mid or wait.begin) - wait.begin
        for other in recs:
            if other is wait or other.is_comm:
                continue
            lo = max(other.begin, open_t)
            hi = min(other.end, close_t)
            if hi > lo:
                h += hi - lo
        e = close_t - (wait.mid or wait.begin)
        return c, h, e

    for recs in by_rs.values():
        recs = sorted(recs, key=lambda x: x.index)
        seen_a2a_waits = set()
        for i, rec in enumerate(recs):
            if rec.opcode == _AR_SEND:
                wait = next(
                    (w for w in recs[i + 1:]
                     if w.opcode == _AR_WAIT
                     and w.layer == rec.layer and w.slot == rec.slot),
                    None,
                )
                if wait is None:
                    continue
                windows += 1
                c, h, e = _window(recs, rec.mid or rec.end, wait.end, wait)
                comm += c
                hidden += h
                exposed += e
            elif rec.opcode == _A2A_SEND and rec.slot == 0:
                # ONE window per gate layer, opened by the phase-0 send
                # (phase 1's bytes ride the same window — it closes at
                # the shared wait's end).
                wait = next(
                    (w for w in recs[i + 1:]
                     if w.opcode == _A2A_WAIT and w.layer == rec.layer),
                    None,
                )
                if wait is None or id(wait) in seen_a2a_waits:
                    continue
                seen_a2a_waits.add(id(wait))
                windows += 1
                a2a_windows += 1
                c, h, e = _window(recs, rec.mid or rec.end, wait.end, wait)
                comm += c
                hidden += h
                exposed += e
                a2a_comm += c
                a2a_hidden += h
                a2a_exposed += e
            elif rec.opcode == _ALLREDUCE and rec.mid:
                windows += 1
                comm += rec.mid - rec.begin
                exposed += rec.mid - rec.begin
            elif rec.opcode == _RING_POLL:
                ring_polls += 1
                ring_doorbells.add(rec.mid)
    return {
        "windows": windows,
        "comm_ticks": int(comm),
        "hidden_ticks": int(hidden),
        "exposed_ticks": int(exposed),
        "hidden_fraction": (hidden / comm) if comm else None,
        "a2a_windows": a2a_windows,
        "a2a_comm_ticks": int(a2a_comm),
        "a2a_hidden_ticks": int(a2a_hidden),
        "a2a_exposed_ticks": int(a2a_exposed),
        "a2a_hidden_fraction": (
            (a2a_hidden / a2a_comm) if a2a_comm else None
        ),
        "ring_polls": ring_polls,
        "ring_doorbell_min": (
            min(ring_doorbells) if ring_doorbells else None
        ),
        "ring_doorbell_max": (
            max(ring_doorbells) if ring_doorbells else None
        ),
    }


def _tick_span(records: list[TaskRecord], rank: int = 0) -> int:
    """Total clock span of one rank's records (seconds scaling base)."""
    mine = [r for r in records if r.rank == rank]
    if not mine:
        return 0
    return max(r.end for r in mine) - min(r.begin for r in mine)


def _overlap_report_array(arr: np.ndarray) -> dict | None:
    """Vectorized :func:`overlap_report` over a raw ring — the inline
    per-launch path (serving decode pays this every traced launch).
    Valid only when every AR_SEND is immediately followed by its
    AR_WAIT along the task axis (what the builder emits and the
    scheduler's sequential-chain deps preserve — tested); returns None
    otherwise and the caller falls back to the general record-wise
    implementation, which stays the semantic reference."""
    ops = arr[..., TR_OPCODE]
    if (ops == _A2A_SEND).any():
        # MoE EP-combine windows span whole expert-GEMM runs (never
        # send-adjacent-to-wait); the record-wise reference handles
        # them — and MoE launches are rare enough per process that the
        # general path's cost is irrelevant.
        return None
    n_sends = int((ops == _AR_SEND).sum())
    mids = arr[..., TR_MID]
    windows = 0
    comm = hidden = exposed = 0
    if n_sends:
        send_adj = (
            (ops[:, :, :-1] == _AR_SEND)
            & (ops[:, :, 1:] == _AR_WAIT)
            & (arr[:, :, :-1, TR_LAYER] == arr[:, :, 1:, TR_LAYER])
            & (arr[:, :, :-1, TR_SLOT] == arr[:, :, 1:, TR_SLOT])
        )
        if int(send_adj.sum()) != n_sends:
            return None  # non-adjacent pair somewhere: general path
        send = arr[:, :, :-1][send_adj]
        wait = arr[:, :, 1:][send_adj]
        open_t = np.where(
            send[:, TR_MID] > 0, send[:, TR_MID], send[:, TR_END]
        )
        wmid = np.where(
            wait[:, TR_MID] > 0, wait[:, TR_MID], wait[:, TR_BEGIN]
        )
        windows += n_sends
        comm += int((wait[:, TR_END] - open_t).sum())
        hidden += int((wmid - wait[:, TR_BEGIN]).sum())
        exposed += int((wait[:, TR_END] - wmid).sum())
    fused = (ops == _ALLREDUCE) & (mids > 0)
    if fused.any():
        c = int((mids[fused] - arr[..., TR_BEGIN][fused]).sum())
        windows += int(fused.sum())
        comm += c
        exposed += c
    rp = ops == _RING_POLL
    rp_mids = mids[rp]
    return {
        "windows": windows,
        "comm_ticks": comm,
        "hidden_ticks": hidden,
        "exposed_ticks": exposed,
        "hidden_fraction": (hidden / comm) if comm else None,
        # Schema parity with overlap_report: no A2A records reached
        # this path (it bails to the record-wise reference on any).
        "a2a_windows": 0,
        "a2a_comm_ticks": 0,
        "a2a_hidden_ticks": 0,
        "a2a_exposed_ticks": 0,
        "a2a_hidden_fraction": None,
        "ring_polls": int(rp.sum()),
        "ring_doorbell_min": (
            int(rp_mids.min()) if rp_mids.size else None
        ),
        "ring_doorbell_max": (
            int(rp_mids.max()) if rp_mids.size else None
        ),
    }


@dataclasses.dataclass
class KernelTraceLaunch:
    """Host-side metadata for one traced launch: the ring (raw and/or
    decoded) plus what only the host knows — wall time, when the
    launch ran (monotonic, comparable to event-ring timestamps), and
    which requests' trace ids occupied the batch slots.

    Engines construct with the RAW ``ring`` array and leave
    ``records`` to decode lazily (:meth:`get_records`): the inline
    per-launch work on the serving decode path is vectorized over the
    raw ring (``observe_launch``); full record decode happens only for
    the rare consumers (the ``kernel_trace`` verb's summary, the
    merged timeline)."""

    wall_s: float
    t0: float
    trace_ids: dict[int, str] = dataclasses.field(default_factory=dict)
    nsteps: int = 0
    launch: int = 0
    records: list[TaskRecord] | None = None
    ring: np.ndarray | None = None
    # Work-ring doorbell the host published for this launch (resident
    # decode; None = ring-less launch). validate_ring checks every
    # RING_POLL record stamped exactly this value.
    doorbell: int | None = None

    def get_records(self) -> list[TaskRecord]:
        if self.records is None:
            self.records = decode_trace(self.ring)
        return self.records

    def summary(self) -> dict:
        records = self.get_records()
        per_op: dict[str, int] = {}
        for rec in records:
            if rec.rank == 0:
                per_op[rec.op] = per_op.get(rec.op, 0) + rec.dur
        return {
            "launch": self.launch,
            "wall_s": self.wall_s,
            "nsteps": self.nsteps,
            "records": len(records),
            "trace_ids": dict(self.trace_ids),
            "ticks_by_opcode": per_op,
            "overlap": overlap_report(records),
        }


def observe_launch(launch: KernelTraceLaunch, registry=None) -> dict:
    """Fold one traced launch into the PR 5 metrics registry:
    ``tdt_mega_task_seconds{opcode}`` histograms (rank 0's records,
    ticks apportioned over the launch's measured wall time) and the
    ``tdt_mega_overlap_exposure`` gauge — measured wall seconds of AR
    comm window that coincided with compute work in this launch (the
    ring-derived replacement for the analytic estimate). Returns the
    overlap report.

    This runs INLINE per traced launch on the serving decode path:
    with a raw ``ring`` attached it is fully vectorized (gap check,
    per-opcode duration grouping, overlap windows) and never
    materializes records — the tracer-overhead budget in
    perf/MEGA_TRACE.json prices exactly this path."""
    reg = registry if registry is not None else obs_metrics.default_registry()
    if launch.ring is not None and launch.records is None:
        arr = _as_ranked(launch.ring)
        if not (arr[..., TR_FLAG] == 1).all():
            decode_trace(arr)  # raises TraceError with the location
        rep = _overlap_report_array(arr)
        if rep is None:
            rep = overlap_report(launch.get_records())
        if not reg.enabled:
            return rep
        r0 = arr[0]
        span = int(r0[..., TR_END].max()) - int(r0[..., TR_BEGIN].min())
        sec_per_tick = (launch.wall_s / span) if span else 0.0
        durs = (r0[..., TR_END] - r0[..., TR_BEGIN]).ravel()
        ops = r0[..., TR_OPCODE].ravel()
        # (opcode, dur) pairs folded into one int64 key: a 1-D unique
        # is several times cheaper than unique(axis=0) on these small
        # arrays, and this runs per traced launch.
        keys = ops.astype(np.int64) * (1 << 32) + durs.astype(np.int64)
        uniq, counts = np.unique(keys, return_counts=True)
        groups = [
            (int(k >> 32), int(k & 0xFFFFFFFF), int(n))
            for k, n in zip(uniq.tolist(), counts.tolist())
        ]
    else:
        records = launch.get_records()
        rep = overlap_report(records)
        if not reg.enabled:
            return rep
        span = _tick_span(records)
        sec_per_tick = (launch.wall_s / span) if span else 0.0
        grouped: dict[tuple, int] = {}
        for rec in records:
            if rec.rank == 0:
                k = (rec.opcode, rec.dur)
                grouped[k] = grouped.get(k, 0) + 1
        groups = [(op, dur, n) for (op, dur), n in grouped.items()]
    hist = reg.histogram(
        "tdt_mega_task_seconds",
        "Per-task device time inside megakernel launches, by opcode "
        "(ring ticks scaled to the launch's measured wall).",
        labels=("opcode",),
    )
    # Grouped by (opcode, ticks): identical durations fold into ONE
    # bucket increment (observe_n) — O(distinct durations) registry
    # ops per launch, not O(records).
    for op, dur, n in groups:
        hist.observe_n(
            dur * sec_per_tick, n,
            opcode=_OP_NAMES.get(op, f"OP{op}"),
        )
    reg.gauge(
        "tdt_mega_overlap_exposure",
        "Measured wall seconds of AR comm window coinciding with "
        "compute in the last traced launch (device ring; hidden comm).",
    ).set(rep["hidden_ticks"] * sec_per_tick)
    reg.gauge(
        "tdt_mega_overlap_hidden_fraction",
        "Measured fraction of AR comm window hidden under compute in "
        "the last traced launch (device ring).",
    ).set(rep["hidden_fraction"] if rep["hidden_fraction"] is not None
          else 1.0)
    return rep


def records_to_chrome(
    launch: KernelTraceLaunch, *, t0_us: float = 0.0
) -> list[dict]:
    """One launch's records as chrome-trace ``X`` events + per-rank
    process metadata. Each rank's device rows live at
    ``rank * _PID_STRIDE + DEVICE_TASK_PID`` — inside that rank's pid
    namespace of the merged host timeline (runtime/profiling.py), so
    Perfetto shows host spans and device tasks per rank side by side.
    Ticks are scaled to microseconds over the launch's wall time; the
    launch's request trace ids ride in every event's args."""
    from triton_distributed_tpu.runtime.profiling import _PID_STRIDE

    records = launch.get_records()
    span = _tick_span(records)
    us_per_tick = (launch.wall_s * 1e6 / span) if span else 1.0
    tids = ",".join(
        launch.trace_ids[k] for k in sorted(launch.trace_ids)
    )
    events: list[dict] = []
    ranks = sorted({r.rank for r in records})
    base_tick = {
        r: min(x.begin for x in records if x.rank == r)
        for r in ranks
    }
    for rank in ranks:
        events.append({
            "ph": "M", "name": "process_name",
            "pid": rank * _PID_STRIDE + DEVICE_TASK_PID,
            "args": {"name": f"rank{rank}: device tasks"},
        })
    for rec in records:
        events.append({
            "ph": "X",
            "name": rec.op,
            "pid": rec.rank * _PID_STRIDE + DEVICE_TASK_PID,
            "tid": rec.step,
            "ts": t0_us + (rec.begin - base_tick[rec.rank]) * us_per_tick,
            "dur": max(rec.dur * us_per_tick, 0.001),
            "args": {
                "task_id": rec.task_id, "layer": rec.layer,
                "slot": rec.slot, "step": rec.step,
                "launch": launch.launch, "trace_ids": tids,
            },
        })
    return events


def merge_with_host_profile(
    name: str, out_dir: str, launches: list[KernelTraceLaunch]
) -> str | None:
    """Merge the ranks' host chrome traces (``merge_group_profile``)
    and append every traced launch's device task rows — ONE file with
    host ``trace_span``s and device tasks, the reference
    ``group_profile`` contract extended below the kernel boundary.
    Launches are laid out sequentially on the merged clock in ``t0``
    order (device ticks are launch-local; only their order and widths
    are meaningful across launches). Returns the merged path; with no
    host traces on disk a device-only timeline is still written."""
    from triton_distributed_tpu.runtime.profiling import (
        merge_group_profile,
    )

    merged_path = merge_group_profile(name, out_dir)
    if merged_path is None:
        root = os.path.join(out_dir, name)
        os.makedirs(root, exist_ok=True)
        merged_path = os.path.join(root, "merged.trace.json.gz")
        data: dict = {"traceEvents": []}
    else:
        with gzip.open(merged_path, "rt") as f:
            data = json.load(f)
    cursor = 0.0
    for launch in sorted(launches, key=lambda x: x.t0):
        evs = records_to_chrome(launch, t0_us=cursor)
        data["traceEvents"].extend(evs)
        cursor += max(launch.wall_s * 1e6, 1.0)
    with gzip.open(merged_path, "wt") as f:
        json.dump(data, f)
    return merged_path
