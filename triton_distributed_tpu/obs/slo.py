"""SLO accounting: declarative deadlines, wire-side goodput.

The serving tier's latency story used to end at histograms — useful
for tail inspection, useless for the question an operator actually
asks: *what fraction of requests met their deadlines?* This module is
the goodput half of the yardstick (ROADMAP item 5, docs/observability.md
"SLO goodput"):

- :class:`SLOSpec` — a named deadline set (TTFT / TPOT / end-to-end,
  seconds; ``None`` = no bound) per priority class. A request names
  its class via the ``slo_class`` payload key; unknown classes fall
  back to ``default``.
- :func:`observe_wire` — folds ONE finished wire-side
  :class:`~triton_distributed_tpu.obs.timeline.Timeline` (the
  streaming path's per-frame stamps — where the user saw the tokens,
  not where the engine latched them) into the registry:
  ``tdt_slo_requests_total{slo_class,outcome}`` (outcome ``met`` /
  ``missed`` / ``cancelled``), ``tdt_slo_violations_total``
  ``{slo_class,deadline}``, and wire-side
  ``tdt_slo_ttft/tpot/e2e_seconds{slo_class}`` histograms.
- :func:`goodput` / :func:`snapshot` — goodput =
  ``met / (met + missed)``. Client-initiated cancellations are
  counted but EXCLUDED from the denominator: a user hanging up is not
  a server miss. The server's ``{"cmd": "slo"}`` verb returns
  :func:`snapshot`.

Evaluation semantics (one rule, applied per configured deadline):

- a measured duration over its bound → violated;
- a deadline that is *unmeasurable on a successful request* (TPOT on
  a 1-token answer, TTFT on a non-streamed payload) → skipped, not
  violated — the spec can only judge what the wire recorded;
- an unmeasurable deadline on a FAILED request → violated: the user
  never got what the deadline promises, and counting a shed request
  as "met its TTFT" would let an overloaded server shed its way to
  100% goodput.
"""

from __future__ import annotations

import dataclasses

from triton_distributed_tpu.obs import metrics as _metrics

# The deadline keys a spec may bound, in reporting order.
DEADLINE_KEYS = ("ttft", "tpot", "e2e")


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One priority class's deadlines, in seconds (None = unbounded).
    An all-None spec still yields outcome accounting: every ``ok``
    request counts ``met`` and every failed one ``missed`` — goodput
    then measures completion, the correct degenerate reading."""

    name: str = "default"
    ttft_s: float | None = None
    tpot_s: float | None = None
    e2e_s: float | None = None

    def deadlines(self):
        """``(key, bound_s)`` pairs for the bounds actually set."""
        for key in DEADLINE_KEYS:
            bound = getattr(self, f"{key}_s")
            if bound is not None:
                yield key, float(bound)

    def as_dict(self) -> dict:
        return {
            "name": self.name,
            "ttft_s": self.ttft_s,
            "tpot_s": self.tpot_s,
            "e2e_s": self.e2e_s,
        }


def normalize_specs(specs) -> dict[str, SLOSpec]:
    """Accept a single spec, a ``{class: spec}`` dict, or None; return
    a dict that always carries a ``default`` class (the fallback for
    requests naming no/unknown classes)."""
    if specs is None:
        out: dict[str, SLOSpec] = {}
    elif isinstance(specs, SLOSpec):
        out = {specs.name: specs}
    else:
        out = dict(specs)
    if "default" not in out:
        out["default"] = SLOSpec()
    return out


def evaluate(tl, spec: SLOSpec) -> list[str]:
    """The deadlines of ``spec`` that ``tl`` violated (empty == met).
    ``tl`` must be a finished timeline; see the module docstring for
    the unmeasurable-duration rule."""
    ok = (tl.status or "ok") == "ok"
    violated: list[str] = []
    for key, bound in spec.deadlines():
        measured = getattr(tl, f"{key}_s")
        if measured is None:
            if not ok:
                violated.append(key)
            continue
        if measured > bound:
            violated.append(key)
    return violated


def judge(tl, spec: SLOSpec) -> str:
    """Classify one finished timeline: ``met`` / ``missed`` /
    ``cancelled``. THE outcome rule — :func:`observe_wire` and the
    server's fan-out (non-observing) summary path both call it, so
    child summaries can never desynchronize from the front ledger. A
    failed request is a miss even under an all-None spec: the user
    got an error, and "no deadlines configured" must not let a
    shedding server read as 100% goodput."""
    status = tl.status or "ok"
    if status == "cancelled":
        return "cancelled"
    if evaluate(tl, spec) or status != "ok":
        return "missed"
    return "met"


def _handles(reg) -> dict:
    """Per-registry tdt_slo_* handles, resolved once and cached on the
    registry (the timeline module's ``_handles`` convention —
    ``Registry.clear`` zeroes series in place, so cached handles
    survive test resets)."""
    h = getattr(reg, "_slo_handles", None)
    if h is None:
        h = {
            "requests": reg.counter(
                "tdt_slo_requests_total",
                "Requests judged against their SLO class, by outcome "
                "(met/missed/cancelled).",
                labels=("slo_class", "outcome"),
            ),
            "violations": reg.counter(
                "tdt_slo_violations_total",
                "Deadline violations, by class and which deadline "
                "(ttft/tpot/e2e) — one request can violate several.",
                labels=("slo_class", "deadline"),
            ),
            "ttft": reg.histogram(
                "tdt_slo_ttft_seconds",
                "WIRE-side time to first token (streamed frame "
                "departure), by SLO class.",
                labels=("slo_class",),
            ),
            "tpot": reg.histogram(
                "tdt_slo_tpot_seconds",
                "WIRE-side per-token time after the first frame, by "
                "SLO class.",
                labels=("slo_class",),
            ),
            "e2e": reg.histogram(
                "tdt_slo_e2e_seconds",
                "WIRE-side end-to-end latency, by SLO class.",
                labels=("slo_class",),
            ),
        }
        reg._slo_handles = h
    return h


def observe_wire(tl, spec: SLOSpec | None = None,
                 registry=None) -> str:
    """Fold one FINISHED wire-side timeline into the SLO ledger.
    Returns the outcome: ``met``, ``missed``, or ``cancelled``."""
    reg = registry if registry is not None else _metrics.default_registry()
    spec = spec if spec is not None else SLOSpec()
    h = _handles(reg)
    cls = spec.name
    outcome = judge(tl, spec)
    if outcome == "cancelled":
        h["requests"].inc(slo_class=cls, outcome="cancelled")
        return "cancelled"
    if (tl.status or "ok") == "ok":
        # Latency quantiles describe SERVED requests only: a
        # cancellation's time-to-hangup, a shed's near-zero synthetic
        # e2e, or a failure's partial span would all DEFLATE the
        # served p99s exactly when an operator reads them (failures
        # are counted and violation-labeled, not timed).
        for key in DEADLINE_KEYS:
            measured = getattr(tl, f"{key}_s")
            if measured is not None:
                h[key].observe(measured, slo_class=cls)
    for key in evaluate(tl, spec):
        h["violations"].inc(slo_class=cls, deadline=key)
    h["requests"].inc(slo_class=cls, outcome=outcome)
    return outcome


def goodput(slo_class: str = "default", registry=None) -> float | None:
    """``met / (met + missed)`` for one class; None before any
    judgeable request (cancellations alone don't make a denominator)."""
    reg = registry if registry is not None else _metrics.default_registry()
    h = _handles(reg)
    met = h["requests"].value(slo_class=slo_class, outcome="met")
    missed = h["requests"].value(slo_class=slo_class, outcome="missed")
    total = met + missed
    if total <= 0:
        return None
    return met / total


def snapshot(specs=None, registry=None) -> dict:
    """The ``{"cmd": "slo"}`` payload: per observed class — outcome
    counts, goodput, wire-side p50/p99 TTFT/TPOT/e2e — plus the
    deployed specs so a scraper sees the deadlines the numbers were
    judged against."""
    reg = registry if registry is not None else _metrics.default_registry()
    h = _handles(reg)
    specs = normalize_specs(specs)
    classes: set[str] = set(specs)
    # list() first: a concurrent observe_wire may grow the series dict
    # mid-scrape (the slo verb is engine-lock-free by design).
    for key in list(getattr(h["requests"], "_series", {})):
        classes.add(key[0])
    out: dict = {"classes": {}, "specs": {
        name: spec.as_dict() for name, spec in sorted(specs.items())
    }}
    for cls in sorted(classes):
        met = h["requests"].value(slo_class=cls, outcome="met")
        missed = h["requests"].value(slo_class=cls, outcome="missed")
        cancelled = h["requests"].value(slo_class=cls, outcome="cancelled")
        entry = {
            "met": met,
            "missed": missed,
            "cancelled": cancelled,
            "goodput": goodput(cls, reg),
            "violations": {
                key: h["violations"].value(slo_class=cls, deadline=key)
                for key in DEADLINE_KEYS
            },
        }
        for key in DEADLINE_KEYS:
            hist = h[key]
            entry[f"{key}_p50_s"] = hist.quantile(0.50, slo_class=cls)
            entry[f"{key}_p99_s"] = hist.quantile(0.99, slo_class=cls)
        out["classes"][cls] = entry
    return out
