"""Native (C++) library: build-on-demand, ctypes bindings, FFI targets.

Parity role: the reference builds its native pieces as a torch extension
(``csrc/lib/op_pybind.cc``, registry ``csrc/lib/registry.h:38-39``) and a
C AOT runtime (``tools/runtime/triton_aot_runtime.cc``). Here one shared
library ``libtdt_native.so`` carries both: the MoE align/sort op (exposed
as an XLA FFI custom call + a plain C host entry) and the AOT archive C
API. pybind11 is not assumed — bindings are ctypes over ``extern "C"``
plus XLA FFI handler capsules (the no-framework equivalents).

Build: g++ at first use, cached next to the package (ignored by git);
everything degrades gracefully to pure-JAX/Python fallbacks when a
toolchain is unavailable (``native_available()`` gates call sites).
"""

from __future__ import annotations

import ctypes
import functools
import os
import subprocess

import jax

_CSRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc")
_OUT_DIR = os.path.join(os.path.dirname(__file__), "_native")
_LIB = os.path.join(_OUT_DIR, "libtdt_native.so")
_SOURCES = ("moe_utils.cc", "aot_runtime.cc")


def _sources_mtime() -> float:
    return max(os.path.getmtime(os.path.join(_CSRC, s)) for s in _SOURCES)


def build(force: bool = False) -> str:
    """Compile csrc/ into libtdt_native.so (no-op when fresh)."""
    if (
        not force
        and os.path.exists(_LIB)
        and os.path.getmtime(_LIB) >= _sources_mtime()
    ):
        return _LIB
    os.makedirs(_OUT_DIR, exist_ok=True)
    # Compile to a process-private path and rename into place: concurrent
    # builders (pytest workers, serving processes) then never dlopen a
    # half-written library.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-std=c++17", "-O2", "-fPIC", "-shared",
        "-I", jax.ffi.include_dir(),
        *[os.path.join(_CSRC, s) for s in _SOURCES],
        "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return _LIB


class NativeLib:
    """ctypes view of libtdt_native.so with typed signatures."""

    def __init__(self, path: str):
        self.path = path
        self.cdll = ctypes.CDLL(path)
        c = self.cdll
        i32p = ctypes.POINTER(ctypes.c_int32)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        c.tdt_moe_align_block_size_host.restype = ctypes.c_int
        c.tdt_moe_align_block_size_host.argtypes = [
            i32p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
            i32p, ctypes.c_int64, i32p, ctypes.c_int64, i32p,
        ]
        c.tdt_aot_open.restype = ctypes.c_void_p
        c.tdt_aot_open.argtypes = [ctypes.c_char_p]
        c.tdt_aot_num_entries.restype = ctypes.c_int
        c.tdt_aot_num_entries.argtypes = [ctypes.c_void_p]
        c.tdt_aot_entry_name.restype = ctypes.c_char_p
        c.tdt_aot_entry_name.argtypes = [ctypes.c_void_p, ctypes.c_int]
        c.tdt_aot_entry_meta.restype = ctypes.c_char_p
        c.tdt_aot_entry_meta.argtypes = [ctypes.c_void_p, ctypes.c_int]
        c.tdt_aot_entry_data.restype = u8p
        c.tdt_aot_entry_data.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_uint64)
        ]
        c.tdt_aot_find.restype = ctypes.c_int
        c.tdt_aot_find.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        c.tdt_aot_close.restype = None
        c.tdt_aot_close.argtypes = [ctypes.c_void_p]
        c.tdt_aot_write.restype = ctypes.c_int
        c.tdt_aot_write.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64),
        ]
        self._ffi_registered = False

    def register_ffi_targets(self) -> None:
        """Register the XLA FFI custom calls on the CPU platform
        (host-side planning ops; TPU in-jit paths use the pure-JAX
        equivalents — XLA custom calls execute on the host there)."""
        if self._ffi_registered:
            return
        handler = jax.ffi.pycapsule(self.cdll.TdtMoeAlignBlockSize)
        jax.ffi.register_ffi_target(
            "tdt_moe_align_block_size", handler, platform="cpu"
        )
        self._ffi_registered = True


@functools.cache
def get_native() -> NativeLib | None:
    """Build + load the native lib; None when no toolchain is present."""
    try:
        return NativeLib(build())
    except (OSError, subprocess.CalledProcessError):
        return None


def native_available() -> bool:
    return get_native() is not None
