"""Analytic GEMM / communication time models for autotuner pruning.

Parity: reference ``kernels/nvidia/gemm_perf_model.py`` (tensor-core
roofline from clock rate × subcores) and ``comm_perf_model.py``
(``estimate_reduce_scatter_time_ms`` / ``estimate_all_gather_time_ms``
from NVLink/NIC bandwidth, :97-116). The TPU translation replaces the
CUDA-capability table with a chip-spec table (MXU TFLOPs, HBM GB/s, ICI
GB/s per link) and the NVLink/NIC split with the ICI/DCN split.

Numbers are public per-chip specs (the same ones the scaling-book recipe
uses for its roofline arithmetic); unknown chips fall back to v5e.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ChipSpec:
    name: str
    bf16_tflops: float       # MXU peak, bf16
    int8_tops: float         # MXU peak, int8
    hbm_gbs: float           # HBM bandwidth GB/s
    ici_gbs_per_link: float  # one ICI link, one direction, GB/s
    ici_links: int           # links per chip (torus degree)
    dcn_gbs: float           # per-host DCN bandwidth GB/s (order-of-magnitude)


_SPECS = {
    "v4": ChipSpec("v4", 275.0, 275.0, 1228.0, 45.0, 6, 25.0),
    "v5p": ChipSpec("v5p", 459.0, 918.0, 2765.0, 90.0, 6, 25.0),
    "v5e": ChipSpec("v5e", 197.0, 394.0, 819.0, 45.0, 4, 25.0),
    "v6e": ChipSpec("v6e", 918.0, 1836.0, 1640.0, 90.0, 4, 25.0),
}


@functools.lru_cache()
def chip_spec(device_kind: str | None = None) -> ChipSpec:
    """Resolve the spec of the current (or named) chip generation."""
    if device_kind is None:
        devs = jax.devices()
        device_kind = devs[0].device_kind if devs else "cpu"
    kind = device_kind.lower().replace(" ", "")
    for key in ("v6e", "v6lite", "v5p", "v5e", "v5lite", "v4"):
        if key in kind:
            return _SPECS.get(key.replace("lite", "e"), _SPECS["v5e"])
    return _SPECS["v5e"]


def measured_anchors(path: str | None = None) -> dict | None:
    """Load recorded on-chip measurements (``perf/MEASURED.json``).

    VERDICT r2 weak #2: projections fed by datasheet constants are not
    anchored to what the hardware actually delivers. The anchors file
    records probe-measured HBM bandwidth and a measured GEMM at the
    north-star shape (provenance inside the file); ``anchored_spec``
    turns them into an effective ChipSpec.
    """
    if path is None:
        path = os.environ.get("TDT_MEASURED_JSON")
    if path is None:
        here = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        path = os.path.join(here, "perf", "MEASURED.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def anchored_spec(
    anchors: dict | None = None, base: ChipSpec | None = None
) -> tuple[ChipSpec, dict]:
    """Effective ChipSpec derived from measurements, plus metadata.

    - ``hbm_gbs``: the probe-measured number outright.
    - ``bf16_tflops``: effective MXU rate solved from the measured
      north-star GEMM (captures real MXU efficiency + relay dispatch
      amortization — ~3x below datasheet peak on the v5e, which is what
      any projection fed by peak silently hides).
    - ``ici_gbs_per_link``: unmeasurable on one chip; derated by the
      measured/datasheet HBM fraction as a documented same-fabric-class
      proxy. Error bars from the recorded cross-process relay variance.

    Returns ``(spec, meta)`` where ``meta`` carries ``error_bars_frac``
    and per-field provenance strings. Falls back to the datasheet spec
    (with ``anchored: False``) when no measurements are recorded.
    """
    anchors = anchors if anchors is not None else measured_anchors()
    base = base or chip_spec((anchors or {}).get("chip"))
    if not anchors:
        return base, {"anchored": False}
    hbm = float(anchors.get("hbm_gbs", base.hbm_gbs))
    hbm_frac = hbm / base.hbm_gbs
    tflops = base.bf16_tflops
    g = anchors.get("gemm_anchor")
    if g:
        ideal_flops = 2.0 * g["m"] * g["n"] * g["k"]
        tflops = ideal_flops / (g["ms"] * 1e-3) / 1e12
    spec = dataclasses.replace(
        base,
        name=base.name + "-anchored",
        hbm_gbs=hbm,
        bf16_tflops=tflops,
        int8_tops=base.int8_tops * (tflops / base.bf16_tflops),
        ici_gbs_per_link=base.ici_gbs_per_link * hbm_frac,
    )
    meta = {
        "anchored": True,
        "error_bars_frac": float(anchors.get("error_bars_frac", 0.3)),
        "provenance": anchors.get("provenance", {}),
        "hbm_frac_of_datasheet": round(hbm_frac, 3),
        "effective_bf16_tflops": round(tflops, 1),
    }
    return spec, meta


def _dtype_tflops(spec: ChipSpec, dtype) -> float:
    itemsize = jnp.dtype(dtype).itemsize
    if itemsize == 1:
        return spec.int8_tops
    if itemsize >= 4:
        return spec.bf16_tflops / 2  # fp32 runs the MXU at half rate
    return spec.bf16_tflops


def estimate_gemm_time_ms(
    m: int, n: int, k: int, dtype=jnp.bfloat16, spec: ChipSpec | None = None
) -> float:
    """Roofline GEMM estimate: max(MXU time, HBM stream time).

    Parity: ``estimate_matmul_time`` (``gemm_perf_model.py``) — there
    compute/load/store terms from tensor-core TFLOPs + DRAM bandwidth;
    here the same two terms against MXU and HBM peaks. MXU efficiency is
    derated for small/ragged shapes (128-alignment), the TPU analog of
    the reference's wave-quantization term.
    """
    spec = spec or chip_spec()
    itemsize = jnp.dtype(dtype).itemsize
    tflops = _dtype_tflops(spec, dtype)

    def pad(x):  # MXU tiles are 128-aligned; ragged edges burn lanes
        return ((x + 127) // 128) * 128

    eff_flops = 2.0 * pad(m) * pad(n) * pad(k)
    compute_ms = eff_flops / (tflops * 1e12) * 1e3
    bytes_moved = (m * k + k * n) * itemsize + m * n * itemsize
    mem_ms = bytes_moved / (spec.hbm_gbs * 1e9) * 1e3
    return max(compute_ms, mem_ms)


def _ring_bw_gbs(spec: ChipSpec, bidir: bool = True) -> float:
    """Per-chip ring bandwidth over ICI: a 1-D ring uses 2 links per chip
    (one per direction) when the protocol is bidirectional."""
    links = 2 if bidir and spec.ici_links >= 2 else 1
    return spec.ici_gbs_per_link * links


def estimate_reduce_scatter_time_ms(
    nbytes: int,
    world_size: int,
    local_world_size: int | None = None,
    spec: ChipSpec | None = None,
    bidir: bool = True,
) -> float:
    """Ring reduce-scatter estimate over ICI, with a DCN term when the
    axis spans slices.

    Parity: ``estimate_reduce_scatter_time_ms`` (``comm_perf_model.py:97``)
    — intra-node NVLink term + inter-node NIC term, overlapped when
    fullmesh. TPU: intra-slice ICI ring moves (n-1)/n of the payload per
    chip; the inter-slice share rides DCN and dominates when present.
    """
    spec = spec or chip_spec()
    local = local_world_size or world_size
    intra_ms = (
        nbytes * (local - 1) / local / (_ring_bw_gbs(spec, bidir) * 1e9) * 1e3
    )
    if world_size != local:
        nslices = world_size // local
        inter_ms = nbytes / local / (spec.dcn_gbs * 1e9) * 1e3 * (nslices - 1)
        return intra_ms + inter_ms
    return intra_ms


def estimate_all_gather_time_ms(
    nbytes: int,
    world_size: int,
    local_world_size: int | None = None,
    spec: ChipSpec | None = None,
    bidir: bool = True,
) -> float:
    """Same cost shape as reduce-scatter (parity:
    ``comm_perf_model.py:113-116``). ``nbytes`` is the FULL gathered
    size."""
    return estimate_reduce_scatter_time_ms(
        nbytes, world_size, local_world_size, spec, bidir
    )


def estimate_all_reduce_time_ms(
    nbytes: int,
    world_size: int,
    local_world_size: int | None = None,
    spec: ChipSpec | None = None,
) -> float:
    """Two-shot allreduce = RS + AG of the same payload."""
    return 2.0 * estimate_reduce_scatter_time_ms(
        nbytes, world_size, local_world_size, spec
    )


def estimate_straggler_stall_ms(
    lag_ms: float, step_ms: float, n: int, adaptive: bool
) -> float:
    """Expected exposed stall in AG+GEMM when one uniformly-random rank's
    chunk arrives ``lag_ms`` late (the tolerance the reference's
    arrival-adaptive tile swizzles buy, ``threadblock_swizzle_ag_moe.py``).

    Static ring order meets the laggard's chunk at position
    ``p = (r - me) mod n`` and stalls ``max(0, lag - p*step)`` — for a
    next-door laggard almost the whole lag is exposed. The adaptive
    schedule (``AGGemmConfig(adaptive=True)``) defers any not-yet-landed
    chunk behind every landed one, so the laggard is met at position
    ``n-1``: exposure is only what (n-2) other chunks' compute could
    not cover.

    PRECONDITION of the adaptive formula: the overlap regime —
    ``step_ms`` at least the per-chunk wire time, so every non-laggard
    chunk has landed by the first step boundary. When compute is faster
    than the wire, the kernel's probe can be inconclusive and its
    fallback blocks in ring order (see the config docstring); this
    model then OVERSTATES the adaptive tolerance — don't capacity-plan
    from it outside the compute-bound regime.
    """
    if adaptive:
        return max(0.0, lag_ms - (n - 1) * step_ms)
    stalls = [max(0.0, lag_ms - p * step_ms) for p in range(1, n)]
    return sum(stalls) / len(stalls) if stalls else 0.0


def prune_configs_by_model(configs, est_fn, top_k: int = 8):
    """Keep the ``top_k`` configs by estimated time.

    Parity: the reference prunes its autotune space with the perf models
    (``gemm_perf_model.py`` used via ``triton.autotune`` ``prune_configs_by``).
    ``est_fn(config) -> ms``.
    """
    if len(configs) <= top_k:
        return list(configs)
    return sorted(configs, key=est_fn)[:top_k]
