"""AOT compile/export: serialize jitted programs into a native archive.

Parity: reference ``tools/compile_aot.py:61-298`` (AOT-compile Triton
kernels to C-callable cubins with algo-info structs) + the C runtime
``tools/runtime/triton_aot_runtime.cc``. TPU translation (SURVEY.md §2.1
"AOT runtime"): AOT = ``jax.export`` — a jitted function lowers to
serialized StableHLO with a stable calling convention; the archive
container + loader are native C++ (``csrc/aot_runtime.cc``), and the
algo-info struct becomes a JSON metadata blob per entry (shapes, dtypes,
static config) that C++ serving hosts can read without deserializing the
program.
"""

from __future__ import annotations

import ctypes
import dataclasses
import json
from typing import Any, Callable, Sequence

import jax
from jax import export as jax_export

from triton_distributed_tpu.native import get_native


@dataclasses.dataclass
class AotEntry:
    """One exported program (parity: a compiled kernel + algo-info)."""

    name: str
    meta: dict[str, Any]
    data: bytes


def export_fn(
    fn: Callable,
    args: Sequence[Any],
    name: str,
    *,
    meta: dict[str, Any] | None = None,
    platforms: Sequence[str] | None = None,
) -> AotEntry:
    """Lower + serialize ``jax.jit(fn)(*args)`` (parity: one
    ``compile_aot`` kernel entry). ``args`` may be arrays or
    ShapeDtypeStructs; shapes/dtypes are recorded as metadata."""
    specs = [
        x if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(jax.numpy.shape(x), jax.numpy.result_type(x))
        for x in jax.tree.leaves(list(args))
    ]
    from triton_distributed_tpu.ops.common import portable_export

    with portable_export():
        exported = jax_export.export(jax.jit(fn), platforms=platforms)(*args)
    full_meta = {
        "arg_shapes": [list(s.shape) for s in specs],
        "arg_dtypes": [str(s.dtype) for s in specs],
        "out_tree": str(exported.out_tree),
        "platforms": list(exported.platforms),
        **(meta or {}),
    }
    return AotEntry(name=name, meta=full_meta, data=bytes(exported.serialize()))


def write_archive(path: str, entries: Sequence[AotEntry]) -> None:
    """Write entries through the native C writer (tdt_aot_write)."""
    lib = get_native()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++?)")
    n = len(entries)
    names = (ctypes.c_char_p * n)(*[e.name.encode() for e in entries])
    metas = (ctypes.c_char_p * n)(
        *[json.dumps(e.meta).encode() for e in entries]
    )
    bufs = [
        ctypes.create_string_buffer(bytes(e.data), max(len(e.data), 1))
        for e in entries
    ]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    datas = (u8p * n)(*[ctypes.cast(b, u8p) for b in bufs])
    lens = (ctypes.c_uint64 * n)(*[len(e.data) for e in entries])
    rc = lib.cdll.tdt_aot_write(path.encode(), n, names, metas, datas, lens)
    if rc != 0:
        raise OSError(f"tdt_aot_write failed (rc={rc})")


def read_archive(path: str) -> list[AotEntry]:
    """Read an archive through the native C loader."""
    lib = get_native()
    if lib is None:
        raise RuntimeError("native library unavailable (no g++?)")
    a = lib.cdll.tdt_aot_open(path.encode())
    if not a:
        raise OSError(f"cannot open AOT archive {path}")
    try:
        out = []
        for i in range(lib.cdll.tdt_aot_num_entries(a)):
            name = lib.cdll.tdt_aot_entry_name(a, i).decode()
            meta = json.loads(lib.cdll.tdt_aot_entry_meta(a, i).decode())
            ln = ctypes.c_uint64()
            ptr = lib.cdll.tdt_aot_entry_data(a, i, ctypes.byref(ln))
            data = ctypes.string_at(ptr, ln.value) if ln.value else b""
            out.append(AotEntry(name=name, meta=meta, data=data))
        return out
    finally:
        lib.cdll.tdt_aot_close(a)


def load_entry(path: str, name: str):
    """Deserialize one entry into a callable (parity: the C runtime's
    launch-by-name; Python hosts rehydrate via jax.export)."""
    for e in read_archive(path):
        if e.name == name:
            return jax_export.deserialize(e.data).call
    raise KeyError(f"no AOT entry named {name!r} in {path}")
