"""Distributed-aware autotuning for overlap kernels.

Parity: reference ``python/triton_dist/autotuner.py`` —
``contextual_autotune(is_dist=...)``:97 wraps a thunk so ``triton.autotune``
works on multi-kernel, stateful, multi-rank code paths, and
``_contextual_tuning_run``:155 benches each config (skipping ones that
fault), aggregates timings across ranks with an all-reduce MAX, and
caches the argmin per key.

TPU translation: a "config" is a set of static kernel parameters (tile
sizes, method enums), and benching a config means jit-compiling the
wrapped function with those statics and timing it. The reference's
cross-rank MAX aggregation exists because each CUDA rank times its own
kernel; under JAX's single-controller model a timed ``shard_map`` op
already runs on every device and the host-side wall clock bounds the
slowest device — the MAX is structural. For multi-host meshes the
aggregation hook still applies (over ``jax.distributed`` hosts).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import logging
import os
import tempfile
from typing import Any, Callable, Iterable, Mapping, Sequence

import jax

from triton_distributed_tpu.runtime.utils import perf_func

logger = logging.getLogger("triton_distributed_tpu.autotune")


@dataclasses.dataclass(frozen=True)
class Config:
    """One candidate: kwargs passed to the tuned function.

    Parity: ``triton.Config`` — there meta-kwargs + num_warps/stages;
    here any static kwargs the wrapped function understands.
    """

    kwargs: Mapping[str, Any]

    def __str__(self) -> str:
        return ", ".join(f"{k}={v}" for k, v in sorted(self.kwargs.items()))

    def __hash__(self) -> int:
        return hash(tuple(sorted(self.kwargs.items())))


class KernelError(Exception):
    """A config failed to compile/run (parity: the reference skipping
    ``TritonError`` configs during the sweep)."""


def _log_dir() -> str | None:
    """File logging is opt-in via TDT_AUTOTUNE_LOG_DIR (the reference
    always writes ./.autotune_logs/; that litters the CWD)."""
    return os.environ.get("TDT_AUTOTUNE_LOG_DIR") or None


def _cache_dir() -> str | None:
    """Persistent result cache location. Default on (the reference caches
    argmin per key across runs); TDT_AUTOTUNE_CACHE=0 disables,
    TDT_AUTOTUNE_CACHE_DIR overrides the path."""
    if os.environ.get("TDT_AUTOTUNE_CACHE", "1") in ("0", "false", ""):
        return None
    return os.environ.get("TDT_AUTOTUNE_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "triton_distributed_tpu",
        "autotune",
    )


def _aggregate_max_over_hosts(times_ms: list[float]) -> list[float]:
    """MAX-combine per-config timings across hosts (parity: the
    ``all_reduce(..., MAX)`` in ``_contextual_tuning_run``:155). No-op on
    single-host meshes."""
    if jax.process_count() <= 1:
        return times_ms
    from jax.experimental import multihost_utils
    import numpy as np

    arr = multihost_utils.process_allgather(np.asarray(times_ms))
    return list(np.max(arr, axis=0))


class Autotuner:
    """Caches the fastest ``Config`` per key and replays it.

    The wrapped ``fn(*args, **config.kwargs, **kwargs)`` must be a
    complete runnable op (may invoke several kernels / carry state —
    that's the "contextual" part: whole-op timing, not one kernel).
    """

    def __init__(
        self,
        fn: Callable[..., Any],
        configs: Sequence[Config],
        key: Callable[..., Any] | None = None,
        prune: Callable[[Sequence[Config]], Sequence[Config]] | None = None,
        n_warmup: int = 3,
        n_repeat: int = 5,
        is_dist: bool = False,
    ):
        self.fn = fn
        self.configs = list(configs)
        self.key_fn = key
        self.prune_fn = prune
        self.n_warmup = n_warmup
        self.n_repeat = n_repeat
        self.is_dist = is_dist
        self.cache: dict[Any, Config] = {}
        self.timings: dict[Any, list[tuple[Config, float]]] = {}
        self._log_file = None
        self._disk: dict[str, str] | None = None  # repr(key) -> str(cfg)

    # -- persistence --------------------------------------------------------
    #
    # Disk format: {repr(key): str(config)} per tuned function; a loaded
    # entry is resolved back to a live Config by matching str() against
    # the current config list, so kwargs never need to be JSON-able and a
    # changed config space simply misses. Parity: the reference caches
    # the per-key argmin in-process and logs sweeps; here the argmin also
    # survives process restarts (VERDICT r1 "no persistent cache").

    def _cache_path(self) -> str | None:
        # Multi-host: a disk hit on one host but not another would
        # desynchronize the sweep (the missing host blocks alone in the
        # cross-host MAX allgather) — hosts re-tune instead.
        if jax.process_count() > 1:
            return None
        d = _cache_dir()
        if d is None:
            return None
        # Qualified name + config-space digest: two tuned functions that
        # share a bare __name__ (closures, decorators) must not replay
        # each other's argmin.
        import hashlib

        qual = "{}.{}".format(
            getattr(self.fn, "__module__", ""),
            getattr(self.fn, "__qualname__", getattr(self.fn, "__name__", "fn")),
        ).replace("<", "").replace(">", "")
        space = hashlib.sha1(
            "|".join(sorted(str(c) for c in self.configs)).encode()
        ).hexdigest()[:10]
        return os.path.join(d, f"{qual}-{space}.json")

    def _load_disk(self) -> dict[str, str]:
        if self._disk is None:
            self._disk = {}
            path = self._cache_path()
            if path and os.path.exists(path):
                try:
                    with open(path) as f:
                        self._disk = dict(json.load(f))
                except (OSError, ValueError):
                    self._disk = {}
        return self._disk

    def _disk_lookup(self, key: Any) -> Config | None:
        entry = self._load_disk().get(repr(key))
        if entry is None:
            return None
        for cfg in self.configs:
            if str(cfg) == entry:
                return cfg
        return None  # config space changed: re-tune

    def _disk_store(self, key: Any, cfg: Config) -> None:
        path = self._cache_path()
        if path is None:
            return
        try:
            # Merge over the CURRENT file contents, not the snapshot
            # loaded at first access — another instance may have stored
            # entries in between (lost-update hazard).
            disk: dict[str, str] = {}
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        disk = dict(json.load(f))
                except (OSError, ValueError):
                    disk = {}
            disk[repr(key)] = str(cfg)
            self._disk = disk
            os.makedirs(os.path.dirname(path), exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
            with os.fdopen(fd, "w") as f:
                json.dump(disk, f, indent=1)
            os.replace(tmp, path)  # atomic: concurrent readers see old/new
        except OSError as e:  # cache is best-effort; never fail the op
            logger.warning("autotune cache write failed: %s", e)

    # -- logging ------------------------------------------------------------

    def _log(self, msg: str) -> None:
        logger.info(msg)
        d = _log_dir()
        if d:
            if self._log_file is None:
                os.makedirs(d, exist_ok=True)
                rank = jax.process_index()
                self._log_file = open(
                    os.path.join(d, f"rank-{rank}.log"), "a", buffering=1
                )
            print(msg, file=self._log_file, flush=True)

    # -- tuning -------------------------------------------------------------

    def _key(self, args, kwargs):
        if self.key_fn is not None:
            return self.key_fn(*args, **kwargs)

        def part(a):
            if hasattr(a, "shape") and hasattr(a, "dtype"):
                return (tuple(a.shape), str(a.dtype))
            if isinstance(a, (int, float, str, bool)):
                return a
            return None

        parts = [part(a) for a in args]
        parts += [(k, part(v)) for k, v in sorted(kwargs.items())]
        return tuple(p for p in parts if p is not None)

    def _effective(self) -> tuple[bool, int, int]:
        """(is_dist, n_repeat, n_warmup) with any enclosing
        ``contextual_autotune`` override applied (None = keep own)."""
        is_dist, n_repeat, n_warmup = self.is_dist, self.n_repeat, self.n_warmup
        if _context_overrides:
            c_dist, c_rep, c_warm = _context_overrides[-1]
            is_dist = c_dist if c_dist is not None else is_dist
            n_repeat = c_rep if c_rep is not None else n_repeat
            n_warmup = c_warm if c_warm is not None else n_warmup
        return is_dist, n_repeat, n_warmup

    def _bench_config(self, cfg: Config, args, kwargs) -> float:
        def thunk():
            return self.fn(*args, **{**kwargs, **cfg.kwargs})

        _, n_repeat, n_warmup = self._effective()
        _, ms = perf_func(thunk, iters=n_repeat, warmup_iters=n_warmup)
        return ms

    def __call__(self, *args, **kwargs):
        if len(self.configs) <= 1:
            cfg = self.configs[0] if self.configs else Config({})
            return self.fn(*args, **{**kwargs, **cfg.kwargs})

        key = self._key(args, kwargs)
        cfg = self.cache.get(key)
        if cfg is None:
            cfg = self._disk_lookup(key)
            if cfg is not None:
                self.cache[key] = cfg
        if cfg is not None:
            return self.fn(*args, **{**kwargs, **cfg.kwargs})

        candidates = list(
            self.prune_fn(self.configs) if self.prune_fn else self.configs
        )
        # Failed configs record inf so the per-config vector stays aligned
        # across hosts for the MAX aggregation; a config that fails the
        # same way on every host (compile error, bad tile) is rejected
        # everywhere. NOTE: a config whose *collective* faults on only a
        # subset of hosts can still desynchronize the sweep (the healthy
        # hosts block inside the collective) — same exposure as the
        # reference; prune such configs ahead of time via ``prune``.
        times_ms: list[float] = []
        for i, cand in enumerate(candidates):
            try:
                ms = self._bench_config(cand, args, kwargs)
            except Exception as e:  # config doesn't compile/run: skip it
                self._log(
                    f"fn: {getattr(self.fn, '__name__', self.fn)} | key: {key}"
                    f" | config-id: {i} | config: {{{cand}}} | error: {e}"
                )
                times_ms.append(float("inf"))
                continue
            self._log(
                f"fn: {getattr(self.fn, '__name__', self.fn)} | key: {key}"
                f" | config-id: {i} | config: {{{cand}}} | mean latency: {ms} ms"
            )
            times_ms.append(ms)

        if self._effective()[0]:
            times_ms = _aggregate_max_over_hosts(times_ms)
        okay = [
            (c, t) for c, t in zip(candidates, times_ms) if t != float("inf")
        ]
        if not okay:
            raise KernelError("cannot find valid config")
        best, best_ms = min(okay, key=lambda ct: ct[1])
        self._log(
            f"fn: {getattr(self.fn, '__name__', self.fn)} | key: {key}"
            f" | best-config: {{{best}}} | best-latency: {best_ms} ms"
        )
        self.cache[key] = best
        self.timings[key] = okay
        self._disk_store(key, best)
        return self.fn(*args, **{**kwargs, **best.kwargs})


def autotune(
    configs: Iterable[Mapping[str, Any] | Config],
    key: Callable[..., Any] | None = None,
    prune: Callable[[Sequence[Config]], Sequence[Config]] | None = None,
    n_warmup: int = 3,
    n_repeat: int = 5,
    is_dist: bool = False,
):
    """Decorator form (parity: ``triton.autotune`` +
    ``contextual_autotune`` combined — on TPU there is no separate
    kernel-level tuner to patch, so one decorator covers both roles)."""
    cfgs = [c if isinstance(c, Config) else Config(dict(c)) for c in configs]

    def decor(fn):
        return Autotuner(
            fn, cfgs, key=key, prune=prune,
            n_warmup=n_warmup, n_repeat=n_repeat, is_dist=is_dist,
        )

    return decor


def contextual_autotune(
    is_dist: bool | None = None,
    n_repeat: int | None = None,
    n_warmup: int | None = None,
):
    """Parity entry point matching the reference (``autotuner.py:97``):
    wraps a thunk whose inner ops are ``Autotuner`` instances. Under the
    JAX design the inner tuners are already contextual (they time the
    whole wrapped op), so the wrapper's job is to scope overrides: while
    the wrapped fn runs, explicitly-passed ``is_dist`` / ``n_repeat`` /
    ``n_warmup`` replace the inner tuners' own settings (``is_dist``
    gates the cross-host MAX timing aggregation; None leaves each inner
    tuner's value untouched)."""

    def decor(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            _context_overrides.append((is_dist, n_repeat, n_warmup))
            try:
                return fn(*args, **kwargs)
            finally:
                _context_overrides.pop()

        return wrapped

    return decor


# Innermost contextual_autotune override: (is_dist, n_repeat, n_warmup),
# None meaning "keep the inner tuner's own value".
_context_overrides: list[tuple[bool | None, int | None, int | None]] = []
