"""Tools: distributed-aware autotuning, analytic perf models, AOT export.

Parity: reference ``python/triton_dist/autotuner.py`` (contextual
autotuner), ``kernels/nvidia/{gemm,comm}_perf_model.py`` and
``python/triton_dist/tools/`` (AOT compile CLI + C runtime).
"""

from triton_distributed_tpu.tools.autotuner import (  # noqa: F401
    Config,
    autotune,
    contextual_autotune,
)
from triton_distributed_tpu.tools.perf_model import (  # noqa: F401
    ChipSpec,
    chip_spec,
    estimate_all_gather_time_ms,
    estimate_all_reduce_time_ms,
    estimate_gemm_time_ms,
    estimate_reduce_scatter_time_ms,
    prune_configs_by_model,
)
