"""AOT compile CLI: export the serving kernel set into one archive.

Parity: reference ``tools/compile_aot.py:61`` + ``scripts/aot_kernels.txt``
(the flash-decode kernel family precompiled for deployment). TPU analog:
export the jitted decode step and the overlap ops at the model's shapes.

Usage:
    python -m triton_distributed_tpu.tools.compile_aot \
        --model tiny --batch 2 --max-len 128 --tp 1 --out model.tdtaot
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp


def build_entries(model_name: str, batch: int, max_len: int, tp: int):
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime.mesh import initialize_distributed
    from triton_distributed_tpu.tools.aot import export_fn

    ctx = initialize_distributed(tp=tp, devices=jax.devices()[:tp])
    model = AutoLLM.from_pretrained(model_name, ctx=ctx)
    cache = model.new_cache(batch, max_length=max_len)
    tok = jnp.zeros((batch,), jnp.int32)
    step = model.decode_fn("xla")

    entries = [
        export_fn(
            step,
            (model.params, tok, cache),
            name=f"decode_step_b{batch}_s{max_len}",
            meta={
                "model": model_name, "tp": tp, "batch": batch,
                "max_len": max_len, "kind": "decode_step",
            },
        )
    ]

    # The prefill program (the other serving entry point).
    prompt = jnp.zeros((batch, max_len // 2), jnp.int32)
    true_len = jnp.full((batch,), max_len // 2, jnp.int32)
    entries.append(
        export_fn(
            lambda prompt, cache, true_len: model.prefill_batched(
                prompt, cache, "xla", true_len
            ),
            (prompt, cache, true_len),
            name=f"prefill_b{batch}_s{max_len // 2}",
            meta={
                "model": model_name, "tp": tp, "batch": batch,
                "kind": "prefill",
            },
        )
    )

    # The flash-decode kernel family at the model's shapes (parity:
    # scripts/aot_kernels.txt — the reference precompiles exactly this
    # family for serving).
    from triton_distributed_tpu.ops.attention import flash_decode

    c = model.cfg
    n = ctx.axis_size(model.axis)
    hq_loc = c.num_q_heads // n
    hkv_loc = c.num_kv_heads // n  # model __init__ enforces divisibility
    q = jnp.zeros((batch, hq_loc, c.head_dim), c.dtype)
    kv = jnp.zeros((batch, hkv_loc, max_len, c.head_dim), c.dtype)
    kv_len = jnp.full((batch,), max_len // 2, jnp.int32)
    entries.append(
        export_fn(
            lambda q, k, v, kv_len: flash_decode(q, k, v, kv_len),
            (q, kv, kv, kv_len),
            name=f"flash_decode_b{batch}_s{max_len}",
            meta={
                "model": model_name, "tp": tp, "batch": batch,
                "kind": "flash_decode",
            },
        )
    )
    return entries


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="tiny")
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--max-len", type=int, default=512)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--out", required=True)
    args = p.parse_args(argv)

    from triton_distributed_tpu.tools.aot import write_archive

    entries = build_entries(args.model, args.batch, args.max_len, args.tp)
    write_archive(args.out, entries)
    for e in entries:
        print(f"exported {e.name}: {len(e.data)} bytes")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
