"""triton_distributed_tpu — a TPU-native framework for compute–communication
overlapping kernels.

This package provides the capabilities of Triton-distributed (ByteDance Seed's
distributed compiler for overlapping kernels, reference layout documented in
/root/repo/SURVEY.md) re-designed idiomatically for TPU:

- ``runtime``  — mesh/topology, distributed initialization, perf + profiling
  utilities (parity: reference ``python/triton_dist/utils.py``).
- ``language`` — device-side communication primitives for Pallas kernels:
  rank/num_ranks, signal/wait semaphores, remote DMA put/get, put+signal,
  tile barriers (parity: reference ``python/triton_dist/language/`` +
  ``libnvshmem_device.py``, built on ``pltpu.make_async_remote_copy`` and
  ``pltpu.semaphore_signal/wait`` over ICI instead of NVSHMEM).
- ``ops``      — collectives (all-gather, reduce-scatter, all-reduce,
  all-to-all, p2p) and overlapping kernels (AG+GEMM, GEMM+RS, GEMM+AR,
  MoE dispatch/combine, distributed flash-decode, SP attention, ring
  attention) (parity: reference ``python/triton_dist/kernels/``).
- ``parallel`` — TP/EP/SP/PP model-parallel layers (parity: reference
  ``python/triton_dist/layers/``).
- ``models``   — Qwen3 dense + MoE models, KV cache, serving engine
  (parity: reference ``python/triton_dist/models/``).
- ``mega``     — megakernel-style whole-model persistent kernel runtime
  (parity: reference ``python/triton_dist/mega_triton_kernel/``).
- ``tools``    — distributed-aware autotuner, AOT export, trace tooling
  (parity: reference ``python/triton_dist/tools/`` + ``autotuner.py``).
"""

__version__ = "0.1.0"

# Install hasattr-guarded aliases for JAX names this package uses that
# older releases spell differently (no-op on current JAX). Must run
# before any submodule touches jax.lax / pallas.
from triton_distributed_tpu.runtime import jax_compat as _jax_compat  # noqa: F401

from triton_distributed_tpu.runtime import (  # noqa: F401
    DistContext,
    current_context,
    initialize_distributed,
    finalize_distributed,
)
