// AOT artifact container: C-callable archive of exported XLA programs.
//
// Parity: reference python/triton_dist/tools/runtime/triton_aot_runtime.cc
// (+ tools/compile.{c,h}) — there, AOT-compiled cubins plus algo-info
// structs are loaded by a C runtime so serving stacks launch kernels
// without Python. The TPU translation (SURVEY.md §2.1 "AOT runtime"):
// programs are serialized with jax.export (StableHLO + calling
// convention); this library is the native container/loader half — a
// single-file archive holding {name, JSON metadata (the algo-info
// analog: shapes, dtypes, static config), serialized program bytes} with
// a C API for writers (the compile_aot CLI) and readers (C++ serving
// hosts, which hand the bytes to their PJRT runtime; Python readers
// deserialize with jax.export.deserialize).
//
// Format TDTAOT01 (little-endian):
//   u8[8]  magic "TDTAOT01"
//   u32    entry count
//   repeat: u32 name_len, name bytes, u32 meta_len, meta bytes (JSON),
//           u64 data_len, data bytes

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr char kMagic[8] = {'T', 'D', 'T', 'A', 'O', 'T', '0', '1'};

struct Entry {
  std::string name;
  std::string meta;
  std::vector<uint8_t> data;
};

struct Archive {
  std::vector<Entry> entries;
};

bool ReadExact(std::FILE* f, void* dst, size_t n) {
  return std::fread(dst, 1, n, f) == n;
}

bool WriteExact(std::FILE* f, const void* src, size_t n) {
  return std::fwrite(src, 1, n, f) == n;
}

}  // namespace

extern "C" {

typedef struct Archive TdtAotArchive;

// Returns nullptr on malformed/unreadable archives.
TdtAotArchive* tdt_aot_open(const char* path) {
  std::FILE* f = std::fopen(path, "rb");
  if (!f) return nullptr;
  auto fail = [&]() -> TdtAotArchive* {
    std::fclose(f);
    return nullptr;
  };
  // File size bounds every untrusted length field: a corrupt header can
  // otherwise drive a multi-GB resize (bad_alloc across the C boundary).
  if (std::fseek(f, 0, SEEK_END) != 0) return fail();
  long fsize = std::ftell(f);
  if (fsize < 12 || std::fseek(f, 0, SEEK_SET) != 0) return fail();
  uint64_t remaining = static_cast<uint64_t>(fsize) - 12;

  char magic[8];
  if (!ReadExact(f, magic, 8) || std::memcmp(magic, kMagic, 8) != 0) {
    return fail();
  }
  uint32_t count = 0;
  if (!ReadExact(f, &count, 4)) return fail();
  auto* a = new Archive();
  a->entries.reserve(std::min<uint64_t>(count, remaining / 16));
  for (uint32_t i = 0; i < count; ++i) {
    uint32_t name_len = 0, meta_len = 0;
    uint64_t data_len = 0;
    Entry e;
    auto take = [&](uint64_t need) {
      if (need > remaining) return false;
      remaining -= need;
      return true;
    };
    // Account length fields separately: 4u + len wraps in 32-bit
    // arithmetic for len >= 0xFFFFFFFC, defeating the file-size bound.
    if (!ReadExact(f, &name_len, 4) || !take(4) ||
        !take(static_cast<uint64_t>(name_len))) goto bad;
    e.name.resize(name_len);
    if (name_len && !ReadExact(f, e.name.data(), name_len)) goto bad;
    if (!ReadExact(f, &meta_len, 4) || !take(4) ||
        !take(static_cast<uint64_t>(meta_len))) goto bad;
    e.meta.resize(meta_len);
    if (meta_len && !ReadExact(f, e.meta.data(), meta_len)) goto bad;
    if (!ReadExact(f, &data_len, 8) || !take(8) || !take(data_len)) goto bad;
    e.data.resize(data_len);
    if (data_len && !ReadExact(f, e.data.data(), data_len)) goto bad;
    a->entries.push_back(std::move(e));
  }
  std::fclose(f);
  return a;
bad:
  delete a;
  return fail();
}

int tdt_aot_num_entries(const TdtAotArchive* a) {
  return static_cast<int>(a->entries.size());
}

const char* tdt_aot_entry_name(const TdtAotArchive* a, int i) {
  if (i < 0 || i >= static_cast<int>(a->entries.size())) return nullptr;
  return a->entries[i].name.c_str();
}

const char* tdt_aot_entry_meta(const TdtAotArchive* a, int i) {
  if (i < 0 || i >= static_cast<int>(a->entries.size())) return nullptr;
  return a->entries[i].meta.c_str();
}

const uint8_t* tdt_aot_entry_data(const TdtAotArchive* a, int i,
                                  uint64_t* len) {
  if (i < 0 || i >= static_cast<int>(a->entries.size())) return nullptr;
  *len = a->entries[i].data.size();
  return a->entries[i].data.data();
}

int tdt_aot_find(const TdtAotArchive* a, const char* name) {
  for (size_t i = 0; i < a->entries.size(); ++i) {
    if (a->entries[i].name == name) return static_cast<int>(i);
  }
  return -1;
}

void tdt_aot_close(TdtAotArchive* a) { delete a; }

// Writes an archive in one shot. Returns 0 on success.
int tdt_aot_write(const char* path, int n, const char** names,
                  const char** metas, const uint8_t** datas,
                  const uint64_t* data_lens) {
  std::FILE* f = std::fopen(path, "wb");
  if (!f) return 1;
  auto fail = [&]() {
    std::fclose(f);
    std::remove(path);
    return 2;
  };
  uint32_t count = static_cast<uint32_t>(n);
  if (!WriteExact(f, kMagic, 8) || !WriteExact(f, &count, 4)) return fail();
  for (int i = 0; i < n; ++i) {
    uint32_t name_len = static_cast<uint32_t>(std::strlen(names[i]));
    uint32_t meta_len = static_cast<uint32_t>(std::strlen(metas[i]));
    uint64_t data_len = data_lens[i];
    if (!WriteExact(f, &name_len, 4) || !WriteExact(f, names[i], name_len) ||
        !WriteExact(f, &meta_len, 4) || !WriteExact(f, metas[i], meta_len) ||
        !WriteExact(f, &data_len, 8) ||
        (data_len && !WriteExact(f, datas[i], data_len))) {
      return fail();
    }
  }
  if (std::fclose(f) != 0) return 3;
  return 0;
}

}  // extern "C"
