// MoE token-sort / block-align native ops.
//
// Parity: reference csrc/lib/moe_utils.cu:61-356
// (moe_ag_scatter_align_block_size_kernel + parallel variant :195-314) —
// sorts flattened top-k token→expert assignments into expert-contiguous
// order, padding each expert's segment to a multiple of the grouped-GEMM
// block size, and emits the per-block expert map the tile scheduler
// consumes. The reference binds this as a torch extension
// (csrc/lib/op_pybind.cc:31); here the same routine is exposed twice:
//   1. an XLA FFI custom call (CPU platform) usable inside jit, and
//   2. a plain C entry point for the ctypes host-planning path.
// TPU grouped GEMM (jax.lax.ragged_dot) consumes group_sizes directly, so
// on-device the pure-JAX composition in ops/moe/routing.py is the default;
// this native variant keeps the "native stays native" contract (SURVEY.md
// §2.1) and serves host-side planners.

#include <algorithm>
#include <cstdint>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// Core routine, shared by the FFI handler and the C API.
// sorted_ids[cap]: slot -> source index into the flattened [T*k] routing
//   (sentinel n for pad slots). Each expert segment is padded to a
//   multiple of block_size.
// block_expert[bcap]: grouped-GEMM tile -> expert id (-1 past the end).
// counts[2]: {num_blocks, num_padded_slots}.
int AlignBlockSize(const int32_t* eids, int64_t n, int32_t num_experts,
                   int32_t block_size, int32_t* sorted_ids, int64_t cap,
                   int32_t* block_expert, int64_t bcap, int32_t* counts) {
  if (block_size <= 0 || num_experts <= 0) return 1;
  std::vector<int64_t> count(num_experts, 0);
  for (int64_t i = 0; i < n; ++i) {
    int32_t e = eids[i];
    if (e < 0 || e >= num_experts) return 2;
    ++count[e];
  }
  std::vector<int64_t> padded(num_experts), start(num_experts);
  int64_t total_padded = 0;
  for (int32_t e = 0; e < num_experts; ++e) {
    padded[e] = (count[e] + block_size - 1) / block_size * block_size;
    start[e] = total_padded;
    total_padded += padded[e];
  }
  int64_t num_blocks = total_padded / block_size;
  if (total_padded > cap || num_blocks > bcap) return 3;

  std::fill(sorted_ids, sorted_ids + cap, static_cast<int32_t>(n));
  std::vector<int64_t> cursor(start);  // next free slot per expert
  for (int64_t i = 0; i < n; ++i) {    // stable: ascending source index
    sorted_ids[cursor[eids[i]]++] = static_cast<int32_t>(i);
  }
  std::fill(block_expert, block_expert + bcap, -1);
  for (int32_t e = 0; e < num_experts; ++e) {
    for (int64_t b = start[e] / block_size;
         b < (start[e] + padded[e]) / block_size; ++b) {
      block_expert[b] = e;
    }
  }
  counts[0] = static_cast<int32_t>(num_blocks);
  counts[1] = static_cast<int32_t>(total_padded);
  return 0;
}

ffi::Error MoeAlignImpl(ffi::Buffer<ffi::S32> expert_ids,
                        ffi::Result<ffi::Buffer<ffi::S32>> sorted_ids,
                        ffi::Result<ffi::Buffer<ffi::S32>> block_expert,
                        ffi::Result<ffi::Buffer<ffi::S32>> counts,
                        int32_t num_experts, int32_t block_size) {
  if (counts->element_count() < 2) {
    return ffi::Error::InvalidArgument("counts must have >= 2 elements");
  }
  int rc = AlignBlockSize(
      expert_ids.typed_data(), expert_ids.element_count(),
      num_experts, block_size, sorted_ids->typed_data(),
      sorted_ids->element_count(), block_expert->typed_data(),
      block_expert->element_count(), counts->typed_data());
  switch (rc) {
    case 0:
      return ffi::Error::Success();
    case 2:
      return ffi::Error::InvalidArgument("expert id out of range");
    case 3:
      return ffi::Error::InvalidArgument("output capacity too small");
    default:
      return ffi::Error::InvalidArgument("bad num_experts/block_size");
  }
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    TdtMoeAlignBlockSize, MoeAlignImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::S32>>()
        .Ret<ffi::Buffer<ffi::S32>>()
        .Attr<int32_t>("num_experts")
        .Attr<int32_t>("block_size"));

extern "C" {

// ctypes host-planning entry (parity: the torch-extension host op).
int tdt_moe_align_block_size_host(const int32_t* eids, int64_t n,
                                  int32_t num_experts, int32_t block_size,
                                  int32_t* sorted_ids, int64_t cap,
                                  int32_t* block_expert, int64_t bcap,
                                  int32_t* counts) {
  return AlignBlockSize(eids, n, num_experts, block_size, sorted_ids, cap,
                        block_expert, bcap, counts);
}

}  // extern "C"
