"""Native (C++) component tests: MoE align op + AOT archive/export.

Parity model (SURVEY.md §4): reference ``test_moe_utils.py`` validates
the CUDA sort against a torch reference; ``test_compile_aot.py`` runs the
AOT-compiled kernels. Here: C++ vs pure-JAX align equality, FFI
custom-call path under jit, archive roundtrip through the C API, and
export → archive → deserialize → run.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from triton_distributed_tpu.native import native_available
from triton_distributed_tpu.ops.moe.routing import (
    align_capacities,
    moe_align_block_size,
)

needs_native = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain"
)


def _random_routing(rng, T=64, k=4, E=16):
    return rng.integers(0, E, size=(T, k)).astype(np.int32), E


class TestAlignJax:
    def test_contract(self, rng):
        eids, E = _random_routing(rng)
        bs = 8
        out = moe_align_block_size(jnp.asarray(eids), E, bs)
        n = eids.size
        cap, bcap = align_capacities(n, E, bs)
        assert out.sorted_ids.shape == (cap,)
        assert out.block_expert.shape == (bcap,)
        counts = np.bincount(eids.reshape(-1), minlength=E)
        padded = (counts + bs - 1) // bs * bs
        assert int(out.num_padded) == padded.sum()
        assert int(out.num_blocks) == padded.sum() // bs

        sids = np.asarray(out.sorted_ids)
        bexp = np.asarray(out.block_expert)
        flat = eids.reshape(-1)
        start = 0
        for e in range(E):
            seg = sids[start:start + padded[e]]
            real = seg[seg < n]
            # every real slot routes to expert e, stably ordered
            assert (flat[real] == e).all()
            assert (np.diff(real) > 0).all() if len(real) > 1 else True
            assert len(real) == counts[e]
            # pad slots carry the sentinel n
            assert (seg[len(real):] == n).all()
            for b in range(start // bs, (start + padded[e]) // bs):
                assert bexp[b] == e
            start += padded[e]
        assert (bexp[int(out.num_blocks):] == -1).all()


@needs_native
class TestAlignNative:
    def test_host_matches_jax(self, rng):
        from triton_distributed_tpu.ops.moe.native_sort import (
            moe_align_block_size_host,
        )

        eids, E = _random_routing(rng, T=128, k=8, E=32)
        bs = 16
        gold = moe_align_block_size(jnp.asarray(eids), E, bs)
        got = moe_align_block_size_host(eids, E, bs)
        np.testing.assert_array_equal(got.sorted_ids, np.asarray(gold.sorted_ids))
        np.testing.assert_array_equal(
            got.block_expert, np.asarray(gold.block_expert)
        )
        assert int(got.num_blocks) == int(gold.num_blocks)
        assert int(got.num_padded) == int(gold.num_padded)

    def test_ffi_under_jit(self, rng):
        from triton_distributed_tpu.ops.moe.native_sort import (
            moe_align_block_size_ffi,
        )

        eids, E = _random_routing(rng)
        bs = 8
        gold = moe_align_block_size(jnp.asarray(eids), E, bs)

        @jax.jit
        def run(x):
            return moe_align_block_size_ffi(x, E, bs)

        got = run(jnp.asarray(eids))
        np.testing.assert_array_equal(
            np.asarray(got.sorted_ids), np.asarray(gold.sorted_ids)
        )
        np.testing.assert_array_equal(
            np.asarray(got.block_expert), np.asarray(gold.block_expert)
        )

    def test_host_rejects_bad_expert(self):
        from triton_distributed_tpu.ops.moe.native_sort import (
            moe_align_block_size_host,
        )

        with pytest.raises(ValueError, match="rc=2"):
            moe_align_block_size_host(np.asarray([[99]], np.int32), 4, 8)


@needs_native
class TestAotArchive:
    def test_roundtrip(self, tmp_path):
        from triton_distributed_tpu.tools.aot import (
            AotEntry,
            read_archive,
            write_archive,
        )

        path = str(tmp_path / "a.tdtaot")
        entries = [
            AotEntry("k1", {"shape": [2, 2]}, b"\x00\x01payload"),
            AotEntry("k2", {"cfg": {"tile": 128}}, b""),
        ]
        write_archive(path, entries)
        got = read_archive(path)
        assert [e.name for e in got] == ["k1", "k2"]
        assert got[0].data == b"\x00\x01payload"
        assert got[0].meta == {"shape": [2, 2]}
        assert got[1].meta["cfg"]["tile"] == 128
        assert got[1].data == b""

    def test_open_rejects_garbage(self, tmp_path):
        from triton_distributed_tpu.native import get_native

        p = tmp_path / "bad.tdtaot"
        p.write_bytes(b"NOTANARCHIVE")
        assert get_native().cdll.tdt_aot_open(str(p).encode()) in (None, 0)

    def test_export_run_roundtrip(self, tmp_path):
        from triton_distributed_tpu.tools.aot import (
            export_fn,
            load_entry,
            write_archive,
        )

        def f(x, y):
            return jnp.dot(x, y) + 1.0

        x = jnp.ones((4, 8), jnp.float32)
        y = jnp.ones((8, 4), jnp.float32)
        e = export_fn(f, (x, y), "matmul", meta={"tile": 4})
        assert e.meta["arg_shapes"] == [[4, 8], [8, 4]]
        path = str(tmp_path / "m.tdtaot")
        write_archive(path, [e])
        g = load_entry(path, "matmul")
        np.testing.assert_allclose(np.asarray(g(x, y)), np.asarray(f(x, y)))
        with pytest.raises(KeyError):
            load_entry(path, "missing")

    def test_compile_aot_cli(self, tmp_path):
        from triton_distributed_tpu.tools.compile_aot import main
        from triton_distributed_tpu.tools.aot import load_entry, read_archive
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.runtime.mesh import (
            finalize_distributed,
            initialize_distributed,
        )

        out = str(tmp_path / "model.tdtaot")
        assert main([
            "--model", "tiny", "--batch", "2", "--max-len", "64",
            "--tp", "1", "--out", out,
        ]) == 0
        entries = read_archive(out)
        assert entries[0].meta["kind"] == "decode_step"

        # Rehydrate and run one decode step.
        finalize_distributed()
        ctx = initialize_distributed(tp=1, devices=jax.devices()[:1])
        model = AutoLLM.from_pretrained("tiny", ctx=ctx)
        cache = model.new_cache(2, max_length=64)
        fn = load_entry(out, entries[0].name)
        logits, _ = fn(model.params, jnp.asarray([1, 2], jnp.int32), cache)
        assert logits.shape == (2, model.cfg.vocab_size)
        assert not np.isnan(np.asarray(logits)).any()
        finalize_distributed()
