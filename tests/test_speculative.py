"""Speculative decoding: drafter/verifier units, KV rollback helpers,
the distribution-preservation statistical proof, and engine-level
bit-identity of speculative greedy decode against the plain path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.models import AutoLLM, sampling
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.paged_kv_cache import (
    PagePool,
    gather_bucket,
    truncate_pages,
)
from triton_distributed_tpu.models.speculative import (
    NGramDraft,
    SpecState,
    cap_draft,
    verify_greedy,
    verify_sampled,
)


# -- drafter ---------------------------------------------------------------


def test_ngram_draft_proposes_previous_continuation():
    d = NGramDraft(max_ngram=3, min_ngram=1)
    d.observe([1, 2, 3, 9, 1, 2, 3])
    # Tail trigram (1,2,3) last continued with 9, 1, 2, ...
    assert d.propose(3) == [9, 1, 2]
    assert d.propose(1) == [9]


def test_ngram_draft_prefers_longest_ngram():
    d = NGramDraft(max_ngram=2, min_ngram=1)
    # Unigram "2" continues with 7 early on; bigram (1, 2) continues
    # with 5 — the bigram match must win over the unigram one.
    d.observe([2, 7, 1, 2, 5, 0, 1, 2])
    assert d.propose(1) == [5]


def test_ngram_draft_no_match_is_empty():
    d = NGramDraft()
    d.observe([1, 2, 3, 4])
    assert d.propose(4) == []       # no token repeats: nothing to look up
    assert d.propose(0) == []
    assert NGramDraft().propose(3) == []  # empty history


def test_ngram_draft_truncates_near_end():
    d = NGramDraft(max_ngram=1)
    d.observe([4, 4])
    # The previous "4" ends at position 1; its continuation is just the
    # final token.
    assert d.propose(5) == [4]


def test_spec_state_adaptive_k():
    st = SpecState(8, k_min=1)
    assert st.k == 8
    st.record(8, 8)
    assert st.k == 8                # capped at k_max
    st.record(8, 3)
    assert st.k == 4                # reset to accepted-run + 1
    st.record(4, 0)
    st.record(2, 0)
    st.record(1, 0)
    assert st.k == 1                # floored at k_min
    st.record(1, 1)
    assert st.k == 3                # full accept grows by 2
    assert st.proposed == 24 and st.accepted == 12
    assert st.accept_rate == pytest.approx(0.5)
    st.record(0, 0)                 # empty drafts never move K
    assert st.k == 3


def test_cap_draft_budget_and_capacity():
    # Budget: never draft past gen budget (emission is draft+1).
    assert cap_draft(8, kv_len=0, budget=4, max_length=1024) == 3
    # Capacity: the padded chunk must fit under max_length.
    assert cap_draft(8, kv_len=100, budget=100, max_length=128) == 8
    assert cap_draft(31, kv_len=96, budget=100, max_length=128) == 31
    assert cap_draft(32, kv_len=96, budget=100, max_length=128) == 31
    # Only a 16-wide chunk fits: 15 drafts + pending pad to exactly 16.
    assert cap_draft(8, kv_len=112, budget=100, max_length=128) == 8
    assert cap_draft(16, kv_len=112, budget=100, max_length=128) == 15
    # Not even the zero-draft 16-wide chunk fits.
    assert cap_draft(8, kv_len=120, budget=100, max_length=128) == -1


# -- verify rules ----------------------------------------------------------


def _one_hotish(seq, v=8, sharp=50.0):
    """Logits [len(seq), v] whose argmax at row i is seq[i]."""
    out = np.zeros((len(seq), v), np.float32)
    for i, t in enumerate(seq):
        out[i, t] = sharp
    return out


def test_verify_greedy_accepts_matching_prefix():
    # Target argmaxes: 3, 5, 2, 7 — draft [3, 5, 9] accepts 2 then
    # corrects with the target's own token at the mismatch position.
    logits = _one_hotish([3, 5, 2, 7])
    a, nxt = verify_greedy(logits, [3, 5, 9])
    assert (a, nxt) == (2, 2)
    a, nxt = verify_greedy(logits, [3, 5, 2])
    assert (a, nxt) == (3, 7)       # full accept → bonus token
    a, nxt = verify_greedy(logits, [])
    assert (a, nxt) == (0, 3)       # zero-draft chunk == plain decode


def test_verify_sampled_preserves_target_distribution():
    """The acceptance-criteria statistical test: with a fixed draft
    token, the FIRST emitted token's empirical distribution over many
    keys must match the filtered target distribution — rejection
    sampling changes latency, never the law."""
    rng = np.random.default_rng(0)
    v = 8
    logits = np.asarray(rng.normal(size=(2, v)) * 1.5, np.float32)
    t, p, k = 0.9, 0.95, 6
    target = np.asarray(sampling.target_probs(
        jnp.asarray(logits[0]), t, p, k), np.float64)
    draft_tok = int(np.argsort(target)[-2])  # plausible but not argmax
    n = 4000
    counts = np.zeros(v, np.int64)
    accepted = 0
    for i in range(n):
        a, nxt, _ = verify_sampled(
            logits, [draft_tok], jax.random.key(i), t, p, k
        )
        first = draft_tok if a >= 1 else nxt
        counts[first] += 1
        accepted += a
    emp = counts / n
    assert np.abs(emp - target).sum() / 2 < 0.05  # total variation
    # Acceptance rate of a delta proposal is exactly p(d).
    assert accepted / n == pytest.approx(float(target[draft_tok]), abs=0.04)


def test_verify_sampled_rejects_zero_probability_draft():
    # A draft outside the filtered support must always be rejected and
    # the replacement drawn from the target support.
    logits = _one_hotish([3], v=8, sharp=50.0)
    for i in range(16):
        a, nxt, _ = verify_sampled(logits, [6], jax.random.key(i), 1.0)
        assert a == 0 and nxt == 3


# -- KV rollback helpers ---------------------------------------------------


def test_truncate_pages_releases_past_keep_len():
    pool = PagePool(8)
    pages = pool.allocate(4)
    free0 = len(pool.free)
    kept = truncate_pages(pool, pages, keep_tokens=33, page_size=16)
    assert kept == pages[:3]        # ceil(33/16) = 3 pages survive
    assert len(pool.free) == free0 + 1


def test_truncate_pages_boundary_and_noop():
    pool = PagePool(8)
    pages = pool.allocate(4)
    # Exactly on a page boundary: keep exactly keep/page pages.
    assert truncate_pages(pool, list(pages), 32, 16) == pages[:2]
    pool.release(pages[:2])
    pages = pool.allocate(4)
    free0 = len(pool.free)
    # keep_tokens covering (or exceeding) the list: no-op.
    assert truncate_pages(pool, pages, 64, 16) == pages
    assert truncate_pages(pool, pages, 999, 16) == pages
    assert len(pool.free) == free0
    # keep_tokens=0 releases everything (the eviction path).
    assert truncate_pages(pool, pages, 0, 16) == []
    assert len(pool.free) == free0 + 4


def test_truncate_pages_protects_shared_prefix():
    pool = PagePool(8)
    pages = pool.allocate(4)
    free0 = len(pool.free)
    # Shared prefix pages (owned by the radix tree) never release here,
    # even when keep_tokens would drop them.
    kept = truncate_pages(pool, pages, 0, 16, shared=2)
    assert kept == pages[:2]
    assert len(pool.free) == free0 + 2
    with pytest.raises(ValueError, match="shared"):
        truncate_pages(pool, pages, 0, 16, shared=7)


def test_gather_bucket_powers_of_two():
    assert gather_bucket(1, 16, 8) == 1
    assert gather_bucket(16, 16, 8) == 1
    assert gather_bucket(17, 16, 8) == 2
    assert gather_bucket(33, 16, 8) == 4
    assert gather_bucket(120, 16, 8) == 8
    assert gather_bucket(999, 16, 8) == 8  # capped at pages_per_seq


def test_rollback_kv_truncates_one_slot(ctx4):
    from triton_distributed_tpu.models.paged_kv_cache import (
        init_paged_cache,
        rollback_kv,
    )

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=64)
    cache, _pool = init_paged_cache(
        model.cfg, 2, model.ctx, model.axis, max_length=64, page_size=16
    )
    cache.kv_len.block_until_ready()
    import dataclasses

    cache = dataclasses.replace(
        cache, kv_len=jnp.asarray([40, 25], jnp.int32)
    )
    cache = rollback_kv(cache, 0, 33)
    np.testing.assert_array_equal(np.asarray(cache.kv_len), [33, 25])


# -- engine integration ----------------------------------------------------


def test_continuous_speculative_greedy_bit_identical(ctx4):
    """The headline exactness proof: speculative greedy decode emits
    the same tokens as plain decode, for repetitive (high-accept) and
    chaotic (rollback-heavy) prompts, and releases every page."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=128)
    prompts = [
        np.asarray([5, 9, 2, 4] * 4, np.int32),     # repetitive
        np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32),
        np.asarray([11, 12, 13, 14], np.int32),
    ]
    gens = [12, 6, 5]
    golds = [
        Engine(model, temperature=0.0).serve(p[None], gen_len=g)[0, len(p):]
        for p, g in zip(prompts, gens)
    ]
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=128, speculative=4
    )
    free0 = len(eng.pool.free)
    outs = eng.run(list(zip(prompts, gens)))
    for got, gold in zip(outs, golds):
        np.testing.assert_array_equal(got, np.asarray(gold))
    assert len(eng.pool.free) == free0
    st = eng.last_stats
    # Ledger consistency: every rejected draft token was rolled back,
    # and target_steps is the verify + batched-decode total.
    assert st["spec_rollback_tokens"] == (
        st["spec_draft_tokens"] - st["spec_accepted_tokens"]
    )
    assert st["target_steps"] == (
        st["decode_steps"] + st["spec_verify_steps"]
    )
    assert st["spec_accepted_tokens"] > 0  # the repetitive prompt drafted


def test_engine_paged_speculative_greedy_bit_identical(ctx4):
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=128)
    prompts = np.asarray(
        [[5, 9, 2, 4] * 2, [7, 1, 3, 8, 6, 2, 4, 9]], np.int32
    )
    gold = Engine(model, temperature=0.0).serve(prompts, gen_len=10)
    eng = Engine(
        model, temperature=0.0, paged=True, page_size=16, speculative=4
    )
    out = eng.serve(prompts, gen_len=10, max_length=128)
    np.testing.assert_array_equal(out, gold)
    st = eng.last_stats
    assert st["spec_verify_steps"] >= 1
    # Per-row ledger: each verify emits accepted+1 for its row, each
    # batched fallback step emits 1 for EVERY row.
    assert (
        st["spec_accepted_tokens"]
        + st["spec_verify_steps"]
        + 2 * st["spec_decode_steps"]
        == 2 * 9
    )
    assert st["target_steps"] == (
        st["spec_verify_steps"] + st["spec_decode_steps"]
    )
    assert st["spec_tokens_per_step"] >= 1.0


def test_speculative_with_prefix_cache_warm_identical(ctx4):
    """speculative=K coexists with prefix_cache=True: warm arrivals map
    shared pages AND speculate, still bit-identical to the dense
    golden."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=128)
    p = np.asarray([5, 9, 2, 4] * 4, np.int32)
    gold = Engine(model, temperature=0.0).serve(p[None], gen_len=12)[0, 16:]
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=128, speculative=4,
        prefix_cache=True, prefill_chunk=16,
    )
    for _ in range(2):  # second arrival is the warm (shared-prefix) one
        outs = eng.run([(p, 12)])
        np.testing.assert_array_equal(outs[0], gold)
    assert eng.last_stats["prefix_hit_tokens"] > 0
    assert eng.last_stats["spec_accepted_tokens"] > 0


def test_speculative_smoke_fast(ctx4):
    """Tier-1 CPU smoke (CI satellite): a short speculative run on both
    engines completes, bit-identical, with the counters present."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=64)
    p = np.asarray([5, 9, 2, 4, 5, 9, 2, 4], np.int32)
    gold = Engine(model, temperature=0.0).serve(p[None], gen_len=6)
    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64, speculative=3
    )
    out = eng.run([(p, 6)])[0]
    np.testing.assert_array_equal(out, gold[0, 8:])
    for key in ("spec_verify_steps", "spec_accept_rate", "target_steps",
                "spec_rollback_tokens"):
        assert key in eng.last_stats


def test_speculative_requires_paged_and_non_mega(ctx4):
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=64)
    with pytest.raises(ValueError, match="paged"):
        Engine(model, speculative=2)
    with pytest.raises(ValueError, match="mega"):
        Engine(model, speculative=2, paged=True, mode="mega")
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    with pytest.raises(ValueError, match="mega"):
        ContinuousEngine(model, mode="mega", speculative=2)


def test_continuous_speculative_sampled_lengths_and_ledger(ctx4):
    """Sampled speculative serving: right lengths, ledger consistent
    (the distribution proof itself is the verify_sampled test)."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=64)
    p = np.asarray([5, 9, 2, 4] * 2, np.int32)
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, speculative=3,
        temperature=0.8, top_p=0.9, top_k=8,
    )
    outs = eng.run([(p, 8), (p, 5)])
    assert [len(o) for o in outs] == [8, 5]
    st = eng.last_stats
    assert st["spec_rollback_tokens"] == (
        st["spec_draft_tokens"] - st["spec_accepted_tokens"]
    )
