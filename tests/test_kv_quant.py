"""Quantized paged KV cache (int8 per-page scales) coverage.

The contract under test (docs/serving.md "Quantized KV cache"):

- quant/dequant round-trips within the symmetric half-step bound,
- the int8 decode/prefill kernels dequantize in-register and match the
  full-width reference within the quantization tolerance (and match a
  reference over the DEQUANTIZED values to float tolerance — the kernel
  math is exactly ``(q @ codes) * scale``),
- prefix-shared pages carry their scales through refcounted sharing,
  COW clones, and eviction/recycling (a recycled page's stale scale is
  reset, never grown),
- ``rollback_kv`` stays consistent on a quantized pool (per-page scales
  are monotone within a page's lifetime, so truncation needs no scale
  write),
- the pool/radix auditor passes with quantization enabled,
- ``kv_dtype`` unset keeps the full-width pytree (and therefore every
  compiled program) bit-identical to the unquantized build.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.models.paged_kv_cache import (
    PagedKVCache,
    append_n,
    as_dense,
    copy_page,
    dequantize_page,
    init_paged_cache,
    kv_bytes_per_token,
    paged_cache_specs,
    quantize_pages,
    quantized_row_scatter,
    rollback_kv,
)
from triton_distributed_tpu.ops.attention import (
    flash_attention,
    flash_decode,
    gqa_decode_reference,
    mha_reference,
    paged_flash_decode,
)
from triton_distributed_tpu.ops.attention.flash_decode import (
    distributed_flash_decode,
    scales_to_dense,
)


def test_quant_roundtrip_error_bound(rng):
    """Symmetric int8 round-trip: |x - deq(quant(x))| ≤ scale/2."""
    x = jnp.asarray(
        rng.standard_normal((3, 4, 16, 32)) * 5.0, jnp.float32
    )
    q, sc = quantize_pages(x)
    assert q.dtype == jnp.int8 and sc.shape == (3, 4)
    back = dequantize_page(q, sc)
    bound = np.asarray(sc)[..., None, None] * 0.5 + 1e-6
    assert np.all(np.abs(np.asarray(back) - np.asarray(x)) <= bound)
    # All-zero input: scale 0, codes 0, round-trip exact (no NaN).
    qz, sz = quantize_pages(jnp.zeros((1, 2, 8, 8)))
    assert np.all(np.asarray(sz) == 0) and np.all(np.asarray(qz) == 0)
    assert np.isfinite(np.asarray(dequantize_page(qz, sz))).all()


def _random_pool(rng, p, hkv, page, d):
    k = jnp.asarray(rng.standard_normal((p, hkv, page, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((p, hkv, page, d)), jnp.float32)
    return k, v


def test_paged_flash_decode_int8_parity(rng):
    """In-kernel dequant == reference over the dequantized view (float
    tolerance) == full-width reference (quant tolerance)."""
    b, hq, hkv, page, pps, p, d = 2, 8, 2, 16, 4, 9, 32
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    k_pool, v_pool = _random_pool(rng, p, hkv, page, d)
    table = jnp.asarray(
        rng.permutation(p - 1)[: b * pps].reshape(b, pps) + 0, jnp.int32
    )
    lens = jnp.asarray([page * pps, 21], jnp.int32)
    k_q, k_sc = quantize_pages(k_pool)
    v_q, v_sc = quantize_pages(v_pool)
    out = paged_flash_decode(
        q, k_q, v_q, table, lens, k_scale=k_sc, v_scale=v_sc
    )
    # Exact contract: the kernel computes attention over codes*scale
    # (pure-XLA reference over the dequantized dense view — no second
    # kernel compile needed).
    from triton_distributed_tpu.ops.attention.flash_decode import (
        pages_to_dense,
    )

    k_deq = pages_to_dense(dequantize_page(k_q, k_sc), table)
    v_deq = pages_to_dense(dequantize_page(v_q, v_sc), table)
    ref_deq = gqa_decode_reference(q, k_deq, v_deq, lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref_deq), atol=2e-4, rtol=2e-4
    )
    # Accuracy contract vs the never-quantized values.
    ref_full = gqa_decode_reference(
        q, pages_to_dense(k_pool, table), pages_to_dense(v_pool, table),
        lens,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref_full), atol=0.1, rtol=0.1
    )


def test_flash_decode_dense_int8_parity(rng):
    """Dense split-KV kernel with per-chunk scales (the layout the
    distributed 1/2-level variants pass through)."""
    b, hq, hkv, s, d, chunk = 2, 8, 2, 256, 64, 64
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    lens = jnp.asarray([200, 47], jnp.int32)
    # Per-chunk quantization: [B, Hkv, C, chunk, d] blocks.
    kc = k.reshape(b, hkv, s // chunk, chunk, d)
    vc = v.reshape(b, hkv, s // chunk, chunk, d)
    k_q, k_sc = quantize_pages(kc)
    v_q, v_sc = quantize_pages(vc)
    out = flash_decode(
        q, k_q.reshape(b, hkv, s, d), v_q.reshape(b, hkv, s, d), lens,
        chunk_k=chunk, k_scale=k_sc, v_scale=v_sc,
    )
    ref = gqa_decode_reference(q, k, v, lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=0.1, rtol=0.1
    )
    with pytest.raises(ValueError, match="together"):
        flash_decode(q, k_q.reshape(b, hkv, s, d),
                     v_q.reshape(b, hkv, s, d), lens,
                     chunk_k=chunk, k_scale=k_sc)


def test_distributed_flash_decode_int8(ctx4, rng):
    """Sequence-sharded int8 decode: per-rank in-kernel dequant, then
    the unchanged (O, LSE) cross-rank combine."""
    b, hq, hkv, s, d, chunk = 2, 4, 2, 256, 64, 64
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    lens = jnp.asarray([180, 47], jnp.int32)
    kc = k.reshape(b, hkv, s // chunk, chunk, d)
    vc = v.reshape(b, hkv, s // chunk, chunk, d)
    k_q, k_sc = quantize_pages(kc)
    v_q, v_sc = quantize_pages(vc)

    def shard_fn(q, k, v, lens, ks, vs):
        return distributed_flash_decode(
            q, k, v, lens, axis="tp", chunk_k=chunk, method="xla",
            k_scale=ks, v_scale=vs, ctx=ctx4,
        )

    f = ctx4.shard_map(
        shard_fn,
        in_specs=(
            P(), P(None, None, "tp", None), P(None, None, "tp", None),
            P(), P(None, None, "tp"), P(None, None, "tp"),
        ),
        out_specs=P(),
    )
    out = f(
        q, k_q.reshape(b, hkv, s, d), v_q.reshape(b, hkv, s, d), lens,
        k_sc, v_sc,
    )
    ref = gqa_decode_reference(q, k, v, lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=0.1, rtol=0.1
    )


def test_flash_attention_int8_parity(rng):
    """Prefill chunk kernel: int8 KV + per-block scales + kv_offset."""
    b, h, d, s_kv, s_q, blk = 1, 2, 32, 128, 32, 16
    q = jnp.asarray(rng.standard_normal((b, h, s_q, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s_kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s_kv, d)), jnp.float32)
    kb = k.reshape(b, h, s_kv // blk, blk, d)
    vb = v.reshape(b, h, s_kv // blk, blk, d)
    k_q, k_sc = quantize_pages(kb)
    v_q, v_sc = quantize_pages(vb)
    off = s_kv - s_q
    out = flash_attention(
        q, k_q.reshape(b, h, s_kv, d), v_q.reshape(b, h, s_kv, d),
        causal=True, kv_offset=off, block_q=16, block_k=blk,
        k_scale=k_sc, v_scale=v_sc,
    )
    ref = mha_reference(q, k, v, causal=True, kv_offset=off)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=0.1, rtol=0.1
    )
    # Dynamic (traced) offset rides scalar prefetch on the same path.
    out_dyn = flash_attention(
        q, k_q.reshape(b, h, s_kv, d), v_q.reshape(b, h, s_kv, d),
        causal=True, kv_offset=jnp.asarray(off, jnp.int32),
        block_q=16, block_k=blk, k_scale=k_sc, v_scale=v_sc,
    )
    np.testing.assert_allclose(
        np.asarray(out_dyn), np.asarray(out), atol=2e-5, rtol=2e-5
    )


def test_quantized_row_scatter_reset_and_grow(rng):
    """A write at page offset 0 RESETS a recycled page's stale scale; a
    mid-page append grows the scale and requantizes earlier rows within
    the new half-step bound."""
    p, h, page, d = 4, 2, 8, 16
    pages = jnp.zeros((p, h, page, d), jnp.int8)
    # Stale tenant: huge scale left on page 2.
    scales = jnp.zeros((p, h), jnp.float32).at[2].set(1e6)
    rows1 = jnp.asarray(rng.standard_normal((4, h, d)), jnp.float32)
    pids = jnp.asarray([2, 2, 2, 2], jnp.int32)
    offs = jnp.asarray([0, 1, 2, 3], jnp.int32)
    pages, scales = quantized_row_scatter(pages, scales, rows1, pids, offs)
    sc_after = np.asarray(scales)[2]
    amax1 = np.max(np.abs(np.asarray(rows1)), axis=(0, 2)) / 127.0
    np.testing.assert_allclose(sc_after, amax1, rtol=1e-6)
    # Grow: append bigger rows mid-page; earlier rows stay within the
    # grown half-step bound.
    rows2 = jnp.asarray(rng.standard_normal((2, h, d)) * 10.0, jnp.float32)
    pages, scales = quantized_row_scatter(
        pages, scales, rows2, jnp.asarray([2, 2], jnp.int32),
        jnp.asarray([4, 5], jnp.int32),
    )
    sc2 = np.asarray(scales)[2]
    assert np.all(sc2 >= sc_after - 1e-9)
    deq = np.asarray(
        dequantize_page(pages, scales)
    )[2][:, :4]  # [h, first 4 rows, d]
    want = np.asarray(rows1).transpose(1, 0, 2)
    # One quantization + one requantization: ≤ 2 half-steps.
    assert np.all(np.abs(deq - want) <= sc2[:, None, None] * 1.0 + 1e-6)


def test_append_n_sequential_scale_protocol(rng):
    """``append_n`` on an int8 pool must leave the pool BIT-IDENTICAL
    to NS single-row ``append`` calls over the same rows: the megakernel
    NS-launch retires pages into the radix tree that unfused serving
    also produces, so the scale grow/requant EVENT ORDER — not just the
    values — must match (append_n sequences its per-step scatters for
    exactly this)."""
    from triton_distributed_tpu.models.paged_kv_cache import append

    L, B, H, NS, page, hd, P_ = 2, 2, 2, 5, 4, 8, 6
    cache = PagedKVCache(
        k_pages=jnp.zeros((L, P_, H, page, hd), jnp.int8),
        v_pages=jnp.zeros((L, P_, H, page, hd), jnp.int8),
        page_table=jnp.asarray([[1, 2, 0], [3, 4, 0]], jnp.int32),
        kv_len=jnp.asarray([2, 3], jnp.int32),
        k_scale=jnp.zeros((L, P_, H), jnp.float32),
        v_scale=jnp.zeros((L, P_, H), jnp.float32),
    )
    # Row magnitudes GROW per step so every append forces a scale grow
    # + requant of the earlier rows — the order-sensitive case.
    k_new = jnp.asarray(
        rng.standard_normal((L, B, H, NS, hd))
        * (2.0 ** np.arange(NS))[None, None, None, :, None],
        jnp.float32,
    )
    v_new = jnp.asarray(rng.standard_normal((L, B, H, NS, hd)),
                        jnp.float32)
    batch = append_n(cache, k_new, v_new)
    seq = cache
    for s in range(NS):
        seq = append(seq, k_new[:, :, :, s, :], v_new[:, :, :, s, :])
    np.testing.assert_array_equal(
        np.asarray(batch.k_pages), np.asarray(seq.k_pages)
    )
    np.testing.assert_array_equal(
        np.asarray(batch.k_scale), np.asarray(seq.k_scale)
    )
    np.testing.assert_array_equal(
        np.asarray(batch.v_pages), np.asarray(seq.v_pages)
    )
    np.testing.assert_array_equal(
        np.asarray(batch.kv_len), np.asarray(seq.kv_len)
    )


def test_append_n_trash_routes_overshoot(rng):
    """``n_valid`` routes a finishing row's guaranteed-overshoot rows
    to the trash page: the sequence's own pages (the ones that retire
    into the radix tree) keep codes AND scales free of garbage-row
    contamination."""
    L, B, H, NS, page, hd, P_ = 1, 2, 1, 4, 4, 8, 4
    cache = PagedKVCache(
        k_pages=jnp.zeros((L, P_, H, page, hd), jnp.int8),
        v_pages=jnp.zeros((L, P_, H, page, hd), jnp.int8),
        page_table=jnp.asarray([[1, 2], [3, 0]], jnp.int32),
        kv_len=jnp.asarray([1, 0], jnp.int32),
        k_scale=jnp.zeros((L, P_, H), jnp.float32),
        v_scale=jnp.zeros((L, P_, H), jnp.float32),
    )
    rows = jnp.asarray(rng.standard_normal((L, B, H, NS, hd)),
                       jnp.float32)
    # Row 0 keeps 2 of 4 rows; row 1 keeps all 4. Make row 0's
    # overshoot HUGE: without routing it would inflate page 1's scale.
    rows = rows.at[:, 0, :, 2:, :].multiply(100.0)
    full = append_n(cache, rows, rows)
    routed = append_n(
        cache, rows, rows, n_valid=jnp.asarray([2, 4], jnp.int32)
    )
    # Routed: page 1 (slot 0's page) scale covers only the 2 kept rows.
    assert float(routed.k_scale[0, 1, 0]) < float(full.k_scale[0, 1, 0])
    # Slot 1 untouched by routing.
    np.testing.assert_array_equal(
        np.asarray(routed.k_pages[:, 3]), np.asarray(full.k_pages[:, 3])
    )
    # Overshoot landed on the trash page (page 0), nowhere else; the
    # kept rows dequantize the same values as an un-routed append of
    # just those rows would.
    clean = append_n(
        cache, rows[:, :, :, :2, :], rows[:, :, :, :2, :],
        n_valid=jnp.asarray([2, 2], jnp.int32),
    )
    np.testing.assert_array_equal(
        np.asarray(routed.k_pages[:, 1]), np.asarray(clean.k_pages[:, 1])
    )


def _tiny_model(ctx, max_length=128):
    from triton_distributed_tpu.models import AutoLLM

    return AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=max_length)


def test_engine_int8_teacher_forced_close(ctx4, rng):
    """Documented accuracy tolerance on the tier-1 smoke model: with the
    SAME token stream fed to a full-width and an int8 engine cache, the
    per-step logits stay within atol 0.25 and the greedy argmax agrees
    on ≥ 80% of steps (the rare flips happen where the full-width
    model's own top1-top2 gap is below the quantization noise)."""
    from triton_distributed_tpu.models.paged_kv_cache import write_prefill

    model = _tiny_model(ctx4)
    prompt = rng.integers(1, 200, size=(2, 24)).astype(np.int32)

    def build(kv_dtype):
        cache, _pool = init_paged_cache(
            model.cfg, 2, ctx4, "tp", max_length=128, page_size=16,
            kv_dtype=kv_dtype,
        )
        dense1 = model.new_cache(1, 128)
        logits = []
        for i in range(2):
            lg, dense1 = model.prefill_batched(
                jnp.asarray(prompt[i : i + 1]), dense1, "xla",
                jnp.asarray([24], np.int32),
            )
            cache = write_prefill(cache, i, dense1.k, dense1.v, 24)
            logits.append(lg[0])
        return jnp.stack(logits), cache

    lf, cf = build(None)
    lq, cq = build("int8")
    # Prefill logits come from the dense forward BEFORE the quantized
    # scatter — identical by construction.
    np.testing.assert_array_equal(np.asarray(lf), np.asarray(lq))
    assert cq.quantized and cq.k_pages.dtype == jnp.int8
    assert kv_bytes_per_token(cq) < kv_bytes_per_token(cf) / 1.9

    tok = jnp.argmax(lf, -1).astype(jnp.int32)
    steps, agree, max_diff = 6, 0, 0.0
    for _ in range(steps):
        lgf, cf = model.decode_step(tok, cf, "xla")
        lgq, cq = model.decode_step(tok, cq, "xla")
        max_diff = max(max_diff, float(jnp.max(jnp.abs(lgf - lgq))))
        agree += int((jnp.argmax(lgf, -1) == jnp.argmax(lgq, -1)).sum())
        tok = jnp.argmax(lgf, -1).astype(jnp.int32)
    assert max_diff < 0.25, f"int8 KV perturbed logits by {max_diff}"
    assert agree >= int(0.8 * 2 * steps), f"argmax agreement {agree}/{2*steps}"


def test_rollback_scales_lockstep(rng):
    """Speculative rollback on a quantized pool: truncate, re-append
    different rows, and the dequantized live prefix still matches the
    full-width history within the quant bound (scales never shrink, so
    the retained rows' codes stay exact)."""
    p, h, page, d, L = 5, 2, 8, 16, 1
    cache = PagedKVCache(
        k_pages=jnp.zeros((L, p, h, page, d), jnp.int8),
        v_pages=jnp.zeros((L, p, h, page, d), jnp.int8),
        page_table=jnp.asarray([[1, 2]], jnp.int32),
        kv_len=jnp.zeros((1,), jnp.int32),
        k_scale=jnp.zeros((L, p, h), jnp.float32),
        v_scale=jnp.zeros((L, p, h), jnp.float32),
    )
    hist_k = []

    def rows():
        r = jnp.asarray(rng.standard_normal((L, 1, h, 1, d)), jnp.float32)
        return r

    for _ in range(6):  # fill 6 rows
        rk, rv = rows(), rows()
        hist_k.append(np.asarray(rk)[:, 0, :, 0])
        cache = append_n(cache, rk, rv)
    # Speculative overshoot: 2 more rows, then reject them.
    cache = append_n(cache, rows(), rows())
    cache = append_n(cache, rows(), rows())
    assert int(cache.kv_len[0]) == 8
    cache = rollback_kv(cache, 0, 6)
    assert int(cache.kv_len[0]) == 6
    # Scales were untouched by the rollback (monotone upper bound).
    sc_before = np.asarray(cache.k_scale)
    # Re-append two fresh rows past the rollback point.
    for _ in range(2):
        rk, rv = rows(), rows()
        hist_k.append(np.asarray(rk)[:, 0, :, 0])
        cache = append_n(cache, rk, rv)
    assert np.all(np.asarray(cache.k_scale) >= sc_before - 1e-9)
    k_dense, _ = as_dense(cache)  # [L, 1, h, S, d] dequantized
    got = np.asarray(k_dense)[:, 0, :, :8]
    want = np.stack(hist_k, axis=2)  # [L, h, 8, d]
    sc = np.asarray(cache.k_scale)  # upper bound on any page's half-step
    # Each of the up-to-7 scale-growing appends requantizes earlier
    # rows by ≤ half a step; bound the accumulated error generously.
    tol = sc.max() * 4.0 + 1e-6
    assert np.all(np.abs(got - want) <= tol)


def test_write_prefill_ignores_stale_scratch_rows(rng):
    """The dense prefill scratch is reused across admissions, so rows
    beyond ``true_len`` hold a PREVIOUS request's KV — the quantized
    scatter must zero them out: same prompt after different
    predecessors must produce byte-identical codes and scales."""
    from triton_distributed_tpu.models.paged_kv_cache import write_prefill

    L, H, S, hd, page = 1, 2, 32, 16, 16
    base = rng.standard_normal((L, 1, H, S, hd)).astype(np.float32)
    g1, g2 = base.copy(), base.copy()
    g1[..., 24:, :] = 77.7     # stale garbage variant A (inflates amax)
    g2[..., 24:, :] = -0.001   # stale garbage variant B

    def fresh():
        return PagedKVCache(
            k_pages=jnp.zeros((L, 4, H, page, hd), jnp.int8),
            v_pages=jnp.zeros((L, 4, H, page, hd), jnp.int8),
            page_table=jnp.asarray([[1, 2]], jnp.int32),
            kv_len=jnp.zeros((1,), jnp.int32),
            k_scale=jnp.zeros((L, 4, H), jnp.float32),
            v_scale=jnp.zeros((L, 4, H), jnp.float32),
        )

    c1 = write_prefill(fresh(), 0, jnp.asarray(g1), jnp.asarray(g1), 24)
    c2 = write_prefill(fresh(), 0, jnp.asarray(g2), jnp.asarray(g2), 24)
    np.testing.assert_array_equal(np.asarray(c1.k_pages),
                                  np.asarray(c2.k_pages))
    np.testing.assert_array_equal(np.asarray(c1.k_scale),
                                  np.asarray(c2.k_scale))
    # And the codes beyond true_len are zero, not quantized garbage.
    assert not np.asarray(c1.k_pages)[:, 2, :, 8:].any()


def test_copy_page_carries_scales(rng):
    L, p, h, page, d = 2, 4, 2, 8, 16
    k = jnp.asarray(rng.standard_normal((L, p, h, page, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((L, p, h, page, d)), jnp.float32)
    k_q, k_sc = quantize_pages(k)
    v_q, v_sc = quantize_pages(v)
    # Snapshot before the copy: copy_page DONATES the cache arrays.
    k_q_np, k_sc_np = np.asarray(k_q), np.asarray(k_sc)
    v_sc_np = np.asarray(v_sc)
    cache = PagedKVCache(
        k_pages=k_q, v_pages=v_q,
        page_table=jnp.zeros((1, 2), jnp.int32),
        kv_len=jnp.zeros((1,), jnp.int32),
        k_scale=k_sc, v_scale=v_sc,
    )
    out = copy_page(cache, 1, 3)
    np.testing.assert_array_equal(np.asarray(out.k_pages)[:, 3], k_q_np[:, 1])
    np.testing.assert_array_equal(np.asarray(out.k_scale)[:, 3], k_sc_np[:, 1])
    np.testing.assert_array_equal(np.asarray(out.v_scale)[:, 3], v_sc_np[:, 1])


def test_prefix_cow_audit_and_speculative_with_quant(ctx4, rng):
    """One serving pass over an int8 pool covering three contracts:

    - a PAGE-ALIGNED shared prefix reuses the cold run's quantized
      pages verbatim → warm output == cold output bit-for-bit,
    - a COW (mid-page) match clones codes+scale and serves cleanly,
    - the pool/radix invariant auditor stays empty throughout,
      including under speculative decoding's verify/rollback churn."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = _tiny_model(ctx4)
    system = rng.integers(1, 200, size=32).astype(np.int32)  # 2 full pages

    # First suffix token differs per arrival → the radix walk stops
    # at the page boundary (no shared child), i.e. no COW.
    reqs = [
        (np.concatenate(
            [system, np.asarray([200 + i], np.int32),
             rng.integers(1, 200, size=7).astype(np.int32)]
        ), 4)
        for i in range(2)
    ]
    warm = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=128,
        prefix_cache=True, kv_dtype="int8",
    )
    cold_outs = [warm.run([r])[0] for r in reqs]   # seeds the tree
    warm_outs = [warm.run([r])[0] for r in reqs]   # reuses shared pages
    assert warm.last_stats["prefix_hit_tokens"] > 0
    for c, w in zip(cold_outs, warm_outs):
        np.testing.assert_array_equal(c, w)
    assert warm.audit() == []
    st = warm.last_stats
    assert st["kv_dtype"] == "int8"
    assert st["kv_bytes_per_token"] < 2 * model.cfg.num_layers * \
        model.cfg.num_kv_heads * model.cfg.head_dim * 2  # < bf16 layout

    # COW path: an arrival sharing a PARTIAL page (prompt diverges
    # mid-page) clones codes+scale and must serve cleanly.
    base = np.concatenate(
        [system, rng.integers(1, 200, size=8).astype(np.int32)]
    )
    alt = base.copy()
    alt[-2:] = (base[-2:] + 1) % 200 + 1  # diverge inside the tail page
    warm.run([(base, 4)])
    warm.run([(alt, 4)])
    assert warm.last_stats["pages_cow_copied"] >= 1
    assert warm.audit() == []

    # Speculative verify/rollback over the same quantized pool (the
    # repetitive prompt guarantees n-gram drafts, hence rollbacks).
    spec = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=128,
        prefix_cache=True, speculative=3, kv_dtype="int8",
    )
    prompt = np.tile(rng.integers(1, 200, size=8).astype(np.int32), 4)
    outs = spec.run([(prompt, 5), (prompt[:20], 4)])
    assert [len(o) for o in outs] == [5, 4]
    assert spec.audit() == []


def test_bf16_bit_identical_when_unset_and_validation(ctx4):
    """kv_dtype unset: the cache pytree (dtypes, structure, specs) is
    EXACTLY the pre-quantization layout — no scale leaves, pool in
    cfg.dtype — so every compiled program and its donation/sharding
    behavior is unchanged. Plus the knob's validation surface."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.models.engine import Engine

    model = _tiny_model(ctx4)
    cache, _pool = init_paged_cache(
        model.cfg, 2, ctx4, "tp", max_length=128, page_size=16
    )
    assert cache.k_scale is None and cache.v_scale is None
    assert not cache.quantized
    assert cache.k_pages.dtype == model.cfg.dtype
    # EXACTLY four array leaves — scale fields are empty subtrees, so
    # every jitted program sees the pre-quantization pytree (same
    # donation indices, same shardings, same compiled cache keys).
    assert len(jax.tree.leaves(cache)) == 4
    specs = paged_cache_specs("tp")
    assert specs.k_scale is None and specs.v_scale is None
    # kv_len-only ops keep the scale-less layout.
    assert rollback_kv(cache, 0, 0).k_scale is None

    with pytest.raises(ValueError, match="unsupported"):
        init_paged_cache(model.cfg, 1, ctx4, "tp", kv_dtype="fp8")
    with pytest.raises(ValueError, match="paged"):
        Engine(model, kv_dtype="int8")
    # PR 7: kv_dtype COMPOSES with mode="mega" (the fused decode
    # dequantizes the int8 pool in-kernel) — construction must succeed;
    # the one remaining mega exclusion is speculative.
    Engine(model, paged=True, mode="mega", kv_dtype="int8")
    ContinuousEngine(model, mode="mega", kv_dtype="int8")
    with pytest.raises(ValueError, match="speculative"):
        ContinuousEngine(model, mode="mega", kv_dtype="int8",
                         speculative=4)
    # cfg-level default plumbs through without the explicit knob.
    cfg = dataclasses.replace(model.cfg, kv_dtype="int8")
    qcache, _ = init_paged_cache(cfg, 1, ctx4, "tp", max_length=128,
                                 page_size=16)
    assert qcache.quantized and qcache.k_pages.dtype == jnp.int8


def test_scales_to_dense_layout():
    scales = jnp.arange(3 * 2, dtype=jnp.float32).reshape(3, 2)  # [P, H]
    table = jnp.asarray([[2, 0]], jnp.int32)
    out = scales_to_dense(scales, table, page=4)  # [1, H, 8]
    assert out.shape == (1, 2, 8)
    np.testing.assert_array_equal(
        np.asarray(out)[0, 1], np.asarray([5, 5, 5, 5, 1, 1, 1, 1], np.float32)
    )
