"""Tree speculation (ISSUE 16): multi-branch draft tries from the radix
tree verified in ONE chunked forward under a tree-attention mask.

Covers the trie builder (shape / budget / mask / rope depths), the
greedy and sampled tree-verify walks (including the distribution-
preservation statistical proof for the sampled walk), the row-move
COMMIT primitive, the radix/tier continuation proposers, the adaptive
width×depth controller, and engine-level bit-identity of tree-
speculative greedy decode against the plain path — including a forced
non-first-branch accept that exercises ``move_kv_rows`` end to end,
seeded-sampled replay across a slot migration with trees on, and the
``spec.verify`` fault seams on the tree path.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.models import AutoLLM, sampling
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.paged_kv_cache import (
    PagePool,
    init_paged_cache,
    move_kv_rows,
)
from triton_distributed_tpu.models.prefix_cache import PrefixCache
from triton_distributed_tpu.models.speculative import (
    SpecState,
    TreeDraft,
    verify_tree_greedy,
    verify_tree_sampled,
)

# Repetitive motif → the radix tree (and the n-gram fallback) actually
# drafts; 4-token period keeps page boundaries interesting at ps=16.
MOTIF = [5, 9, 2, 4]


def golden(model, prompt, gen):
    return Engine(model, temperature=0.0).serve(
        np.asarray([prompt], np.int32), gen_len=gen
    )[0, len(prompt):]


# -- TreeDraft: trie shape, budget, mask, rope depths ----------------------


def test_tree_draft_trie_shape_and_budget():
    """``add_path`` builds a prefix-sharing trie in DFS insertion order
    (parent index < child index — the invariant the leftward row-move
    commit rests on) and stops at the node budget."""
    t = TreeDraft(5)
    assert t.add_path([1, 2, 3]) == 3
    assert t.add_path([1, 4]) == 1      # shares the [1] prefix
    assert t.add_path([7]) == 1
    assert t.tokens == [5, 1, 2, 3, 4, 7]
    assert t.parent == [-1, 0, 1, 2, 1, 0]
    assert t.depth == [0, 1, 2, 3, 2, 1]
    assert not t.is_chain
    assert t.num_drafted == 5 and t.max_depth == 3
    for i, p in enumerate(t.parent[1:], 1):
        assert p < i  # DFS order: storage index ≥ depth
    # Budget truncates, never overflows.
    b = TreeDraft(5)
    assert b.add_path([1, 2, 3, 4, 5], budget=4) == 3
    assert len(b) == 4
    assert b.add_path([1, 9], budget=4) == 0  # full: nothing added
    # Single-path trees are chains (the engines fall back to the
    # linear drafter so non-branching candidates change NOTHING).
    c = TreeDraft(5)
    c.add_path([1, 2, 3])
    assert c.is_chain and c.chain_tokens() == [1, 2, 3]


def test_tree_draft_mask_and_depths():
    """The additive bias lets a node see exactly its root path (so
    sibling branches never attend to each other) and pad rows stay
    plain-causal; ``depths`` ropes every node at its DEPTH — the
    property that makes committed rows bit-identical to
    linearly-written ones."""
    t = TreeDraft(5)
    t.add_path([1, 2, 3])
    t.add_path([1, 4])
    t.add_path([7])
    m = t.mask(8)
    assert m.shape == (8, 8) and m.dtype == np.float32
    # Node 3 (path 5→1→2→3) sees its ancestors, not the [1,4]/[7] limbs.
    assert all(m[3, j] == 0.0 for j in (0, 1, 2, 3))
    assert m[3, 4] < 0 and m[3, 5] < 0
    # Node 4 (path 5→1→4) skips the sibling subtree it forked from.
    assert m[4, 0] == 0.0 and m[4, 1] == 0.0 and m[4, 4] == 0.0
    assert m[4, 2] < 0 and m[4, 3] < 0
    # Pad rows (i ≥ n) are causal so the kernel never sees a
    # fully-masked row.
    assert (m[6, :7] == 0.0).all() and m[6, 7] < 0
    np.testing.assert_array_equal(t.depths(8), [0, 1, 2, 3, 2, 1, 6, 7])


# -- verify walks ----------------------------------------------------------


def test_verify_tree_greedy_walk():
    """The greedy walk draws the target token FIRST (argmax) and only
    then looks for a matching drafted child — acceptance is a
    consequence of the target's choice, never the other way around."""
    t = TreeDraft(5)
    t.add_path([1, 2, 3])
    t.add_path([1, 4])
    t.add_path([7])
    logits = np.full((6, 10), -5.0, np.float32)
    logits[0, 1] = 5.0   # root: target picks 1 → descend node 1
    logits[1, 4] = 5.0   # node 1: target picks 4 → descend node 4
    logits[4, 9] = 5.0   # node 4: target picks 9 → no child, stop
    path, emitted = verify_tree_greedy(logits, t)
    assert path == [1, 4] and emitted == [1, 4, 9]
    # Immediate miss: zero nodes accepted, one token still emitted
    # (the verify forward is never wasted).
    logits[0, 1] = -5.0
    logits[0, 8] = 5.0
    path, emitted = verify_tree_greedy(logits, t)
    assert path == [] and emitted == [8]


def test_verify_tree_sampled_matches_target_distribution():
    """Distribution preservation for the sampled walk: each emitted
    token is drawn from ``target_probs`` of ITS node's logits before
    any accept/descend decision, so the emitted stream's law is
    independent of the draft tree's shape — empirical first-token
    frequencies converge to ``target_probs(logits[0])`` and are
    bit-identical between two different trees under the same keys."""
    rng = np.random.default_rng(7)
    t, p, k = 0.8, 0.9, 5
    wide = TreeDraft(5)
    wide.add_path([1, 2])
    wide.add_path([3, 4])
    wide.add_path([6])
    narrow = TreeDraft(5)
    narrow.add_path([2, 2])
    logits = rng.normal(size=(len(wide), 8)).astype(np.float32) * 2.0
    probs = np.asarray(
        sampling.target_probs(jnp.asarray(logits[0]), t, p, k), np.float64
    )
    n = 1200
    keys = jax.random.split(jax.random.key(11), n)
    first, first_narrow = [], []
    for kk in keys:
        it = iter(jax.random.split(kk, 4))
        _, em = verify_tree_sampled(logits, wide, lambda: next(it), t, p, k)
        first.append(em[0])
        it = iter(jax.random.split(kk, 4))
        _, em = verify_tree_sampled(
            logits[: len(narrow)], narrow, lambda: next(it), t, p, k
        )
        first_narrow.append(em[0])
    emp = np.bincount(first, minlength=8) / n
    assert set(np.nonzero(emp)[0]) <= set(np.nonzero(probs > 0)[0])
    assert np.abs(emp - probs).sum() / 2 < 0.05  # total variation
    # Same keys → same first draw, whatever was drafted.
    assert first == first_narrow


def test_spec_state_record_tree_width_controller():
    """The accept ledger drives BOTH axes: full-depth accepts widen and
    deepen, partial accepts re-aim the depth, zero-accept rounds narrow
    the tree toward the linear chain."""
    st = SpecState(8, w_max=4)
    assert st.width == 4 and st.k == 8  # optimistic start, like k
    st.record_tree(nodes=6, depth=4, accepted=1)    # partial
    assert st.k == 2 and st.width == 4              # re-aim k, keep w
    st.record_tree(nodes=6, depth=3, accepted=3)    # full depth
    assert st.k == 4 and st.width == 4              # k grows, w capped
    st.width = 2
    st.record_tree(nodes=6, depth=3, accepted=3)
    assert st.k == 6 and st.width == 3              # widen on full depth
    st.record_tree(nodes=6, depth=4, accepted=0)    # dry round
    assert st.k == st.k_min and st.width == 2
    for _ in range(5):
        st.record_tree(nodes=6, depth=4, accepted=0)
    assert st.width == 1 and st.k == st.k_min       # floors hold
    assert st.proposed == 54 and st.accepted == 7   # ledger accumulates


# -- the commit primitive --------------------------------------------------


def test_move_kv_rows_permutes_rows_and_refuses_quantized(ctx4):
    """``move_kv_rows`` relocates exactly the named token rows (both K
    and V, every layer, across page boundaries), leaves every other
    slot and row untouched, and refuses quantized pools (whose per-page
    scales would make a row hop a requantization event)."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=64)
    cache, _pool = init_paged_cache(
        model.cfg, 2, model.ctx, model.axis, max_length=64, page_size=16
    )
    shape = cache.k_pages.shape
    rng = np.random.default_rng(3)
    kp = rng.normal(size=shape).astype(np.float32)
    vp = rng.normal(size=shape).astype(np.float32)
    cache = dataclasses.replace(
        cache,
        k_pages=jnp.asarray(kp, cache.k_pages.dtype),
        v_pages=jnp.asarray(vp, cache.v_pages.dtype),
    )
    table = np.asarray(cache.page_table)
    # A tree accept: survivors at storage rows 17,20,21 compact to
    # 9,10,11 — crossing the page-1/page-0 boundary of slot 0.
    src, dst = [17, 20, 21], [9, 10, 11]
    before_k = np.asarray(cache.k_pages, np.float32).copy()
    before_v = np.asarray(cache.v_pages, np.float32).copy()

    def rows(arr, slot, positions):
        ps = shape[3]
        return np.stack([
            arr[:, table[slot, p // ps], :, p % ps, :] for p in positions
        ])

    exp_k, exp_v = rows(before_k, 0, src), rows(before_v, 0, src)
    cache = move_kv_rows(cache, 0, src, dst)
    after_k = np.asarray(cache.k_pages, np.float32)
    after_v = np.asarray(cache.v_pages, np.float32)
    np.testing.assert_array_equal(rows(after_k, 0, dst), exp_k)
    np.testing.assert_array_equal(rows(after_v, 0, dst), exp_v)
    # Slot 1 and slot 0's non-dst rows are untouched.
    np.testing.assert_array_equal(rows(after_k, 1, dst), rows(before_k, 1, dst))
    untouched = [p for p in range(32) if p not in dst]
    np.testing.assert_array_equal(
        rows(after_k, 0, untouched), rows(before_k, 0, untouched)
    )
    np.testing.assert_array_equal(
        rows(after_v, 0, untouched), rows(before_v, 0, untouched)
    )
    # No-op move lists return the cache unchanged (no traced program).
    same = move_kv_rows(cache, 0, [9, 10], [9, 10])
    assert same is cache
    with pytest.raises(ValueError, match="mismatch"):
        move_kv_rows(cache, 0, [1, 2], [1])
    qcache, _qp = init_paged_cache(
        model.cfg, 2, model.ctx, model.axis,
        max_length=64, page_size=16, kv_dtype="int8",
    )
    with pytest.raises(ValueError, match="quantized"):
        move_kv_rows(qcache, 0, [17], [9])


# -- continuation proposers ------------------------------------------------


def test_propose_continuations_radix_walk_and_tiers():
    """The radix proposer walks the FULL history exactly (any mismatch
    → no radix paths — stale branches must not draft), fans out
    recency-first at the frontier, and scans tier chains as a flat
    prefix population; the whole read leaves pins/stats/LRU untouched."""
    pool = PagePool(32)
    pc = PrefixCache(pool, 4)
    pc.insert_chain(pc.root, [1, 2, 3, 4, 5, 6, 7, 8], pool.allocate(2))
    pc.insert_chain(
        pc.root, [1, 2, 3, 4, 9, 9, 9, 9, 9, 9], pool.allocate(3)
    )
    free0 = len(pool.free)
    paths = pc.propose_continuations([1, 2, 3, 4], width=3, depth=4)
    assert sorted(paths) == [[5, 6, 7, 8], [9, 9, 9, 9]]
    # History ending mid-chunk: the chunk tail is the forced stem.
    paths = pc.propose_continuations([1, 2], width=3, depth=4)
    assert sorted(paths) == [[3, 4, 5, 6], [3, 4, 9, 9]]
    # width caps the fan-out; depth truncates each path.
    assert pc.propose_continuations([1, 2, 3, 4], width=1, depth=2) in (
        [[5, 6]], [[9, 9]]
    )
    # Unknown or diverging history proposes nothing.
    assert pc.propose_continuations([42], width=3, depth=4) == []
    assert pc.propose_continuations([1, 2, 7], width=3, depth=4) == []
    # Tier chains: flat scan of evicted-but-resident prefixes.
    paths = pc.propose_continuations(
        [7, 7], width=2, depth=3,
        tier_chains=[[7, 7, 1, 2, 3, 4], [8, 8], [7, 7]],
    )
    assert paths == [[1, 2, 3]]  # strict-extension matches only
    # Pure read: no pages moved, no pins taken.
    assert len(pool.free) == free0
    assert all(n.refcount == 0 for n in pc.walk())


def test_tier_resident_chains_memoized():
    """``PageStore.resident_chains`` decodes only the header chain of
    RAM-resident prefix entries, and its memo invalidates on every
    membership mutation (insert, delete, clear)."""
    from triton_distributed_tpu.models import kv_tier

    tier = kv_tier.PageStore(capacity_bytes=1 << 20)
    assert tier.resident_chains() == []
    z = np.zeros((1, 1, 4, 8), np.float32)
    for chain in ([1, 2, 3, 4], [5, 6, 7, 8]):
        assert tier.put(
            kv_tier.PREFIX_KIND, kv_tier.chain_digest(chain),
            kv_tier.prefix_payload(chain, 4, None, z, z),
        )
    got = tier.resident_chains()
    assert sorted(got) == [[1, 2, 3, 4], [5, 6, 7, 8]]
    assert tier.resident_chains() is got  # memo hit, no rescan
    tier.delete(kv_tier.PREFIX_KIND, kv_tier.chain_digest([1, 2, 3, 4]))
    assert tier.resident_chains() == [[5, 6, 7, 8]]
    tier.clear()
    assert tier.resident_chains() == []
    # Snapshot-kind entries never surface as draft chains.
    tier.put(kv_tier.SNAP_KIND, "s1", {"chain": [9, 9]})
    assert tier.resident_chains() == []


# -- engine integration: greedy bit-identity -------------------------------


def test_continuous_tree_greedy_bit_identical(ctx4):
    """The headline exactness proof for trees: a warmed radix makes the
    drafter propose real multi-branch trees, and the emitted stream
    stays bit-identical to plain greedy decode — with the rollback
    ledger balanced and every page released."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=256)
    p1 = np.asarray(MOTIF * 5 + [3, 5], np.int32)
    p2 = np.asarray(MOTIF * 5 + [9], np.int32)
    g = 32
    golds = [golden(model, list(p), g) for p in (p1, p2)]
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=256,
        speculative=4, spec_width=4, prefix_cache=True,
    )
    assert eng._spec_tree
    free0 = len(eng.pool.free)
    outs = eng.run([(p1, g)])          # warm pass populates the radix
    np.testing.assert_array_equal(outs[0], np.asarray(golds[0]))
    outs = eng.run([(p1, g), (p2, g)])  # warm radix → real trees
    for got, gold in zip(outs, golds):
        np.testing.assert_array_equal(got, np.asarray(gold))
    st = eng.last_stats
    assert st["spec_tree_rounds"] > 0
    assert st["spec_tree_nodes"] >= st["spec_tree_rounds"]
    assert st["spec_tree_depth"] >= st["spec_tree_rounds"]
    assert st["spec_rollback_tokens"] == (
        st["spec_draft_tokens"] - st["spec_accepted_tokens"]
    )
    assert st["target_steps"] == st["decode_steps"] + st["spec_verify_steps"]
    assert eng.audit() == []
    # Pages not held by the radix tree are all back in the pool.
    assert len(eng.pool.free) + eng.prefix.node_count == free0
    assert all(n.refcount == 0 for n in eng.prefix.walk())


def test_engine_paged_tree_greedy_bit_identical(ctx4):
    """The fixed-batch paged Engine grows the same tree arm: its
    persistent radix (prefix_cache=True) feeds the drafter on repeat
    serves, greedy output stays bit-identical, and the ledger closes."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=256)
    # An APERIODIC motif: the n-gram fallback and the radix walk then
    # disagree about the continuation, so the draft really branches
    # (a 4-periodic prompt collapses every proposal into one chain).
    motif = np.random.default_rng(0).integers(1, 50, size=7).tolist()
    p = motif * 4 + [3, 5]
    g = 48
    gold = golden(model, p, g)
    eng = Engine(
        model, temperature=0.0, paged=True, page_size=16,
        speculative=4, spec_width=4, prefix_cache=True,
    )
    assert eng._spec_tree
    for _ in range(2):  # serve 2 re-walks the radix serve 1 populated
        out = eng.serve(np.asarray([p], np.int32), gen_len=g)[0, len(p):]
        np.testing.assert_array_equal(out, np.asarray(gold))
    st = eng.last_stats
    assert st["spec_tree_rounds"] > 0
    assert st["spec_rollback_tokens"] == (
        st["spec_draft_tokens"] - st["spec_accepted_tokens"]
    )


def test_tree_branch_accept_row_moves_bit_identical(ctx4, monkeypatch):
    """Force the target down a NON-first branch every round: the decoy
    branch occupies the early storage rows, so every accept must
    relocate KV rows (``spec_tree_branch_accepts`` counts the moves) —
    and the output must STILL be bit-identical to plain greedy decode,
    proving moved rows equal linearly-written rows."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=256)
    p = MOTIF * 5 + [3, 5]
    g = 24
    gold = [int(t) for t in golden(model, p, g)]
    full = list(p) + gold
    vocab = model.cfg.vocab_size

    def decoy_first(self, tokens, *, width, depth, tier_chains=None):
        pos = len(tokens)
        true = full[pos:pos + depth]
        if len(true) < 2:
            return []
        wrong = max(1, (true[0] + 1) % vocab)
        return [[wrong] * len(true), true]

    monkeypatch.setattr(
        PrefixCache, "propose_continuations", decoy_first
    )
    eng = Engine(
        model, temperature=0.0, paged=True, page_size=16,
        speculative=4, spec_width=4, prefix_cache=True,
    )
    out = eng.serve(np.asarray([p], np.int32), gen_len=g)[0, len(p):]
    np.testing.assert_array_equal(out, np.asarray(gold))
    st = eng.last_stats
    assert st["spec_tree_rounds"] > 0
    assert st["spec_tree_branch_accepts"] > 0  # rows actually moved
    assert st["spec_accepted_tokens"] > 0


# -- sampled replay + migration -------------------------------------------


@pytest.fixture(scope="module")
def one_dev_model():
    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    yield model
    mesh_mod.finalize_distributed()


def test_tree_sampled_replay_and_migration_bit_exact(one_dev_model):
    """Seeded-sampled decode with trees ON is reproducible and survives
    a mid-flight slot migration bit-exactly: the sampled walk draws one
    key per EMITTED token (draft-shape independent), and the snapshot
    carries the PRNG counter plus the width controller's state."""
    from triton_distributed_tpu.models.continuous import (
        ContinuousEngine,
        Request,
    )

    kw = dict(
        max_batch=2, page_size=16, max_length=128, prefix_cache=True,
        speculative=4, spec_width=4, temperature=0.8, seed=11,
    )
    prompts = [np.asarray(MOTIF * 4, np.int32),
               np.asarray(MOTIF * 3 + [7, 7], np.int32)]
    gens = [14, 12]
    work = list(zip(prompts, gens))

    def fresh():
        eng = ContinuousEngine(one_dev_model, **kw)
        assert eng._spec_tree
        return eng

    gold_eng = fresh()
    gold = [r.tokens.tolist() for r in gold_eng.run(work, results=True)]
    assert gold_eng.last_stats["spec_tree_rounds"] >= 0
    # Same seeds, fresh engine → bit-identical replay.
    assert [r.tokens.tolist()
            for r in fresh().run(work, results=True)] == gold
    # Export mid-flight, import into a cold engine: still bit-exact.
    A = fresh()
    A.request_handoff(after_rounds=3)
    res1 = A.run(work, results=True)
    assert all(r.status == "migrated" for r in res1)
    assert A.audit() == []
    B = fresh()
    resume = [Request(p, g, snapshot=r.snapshot)
              for (p, g), r in zip(work, res1)]
    res2 = B.run(resume, results=True)
    assert [r.tokens.tolist() for r in res2] == gold
    assert B.audit() == []


# -- fault seams on the tree path -----------------------------------------


def _tree_engine(ctx, **kw):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx, max_length=128)
    kw.setdefault("max_batch", 1)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_length", 128)
    kw.setdefault("speculative", 4)
    kw.setdefault("spec_width", 4)
    kw.setdefault("prefix_cache", True)
    return model, ContinuousEngine(model, **kw)


def test_tree_verify_fault_isolated(ctx4):
    """A tree verify that raises fails only its own request; the engine
    serves the next request normally and every audit stays clean (the
    failed slot's un-committed tree rows are reclaimed wholesale)."""
    from triton_distributed_tpu.runtime.faults import FaultPlan

    model, eng = _tree_engine(ctx4)
    rep = np.asarray(MOTIF * 4, np.int32)
    gold = golden(model, list(rep), 8)
    eng.run([(rep, 8)])  # warm the radix so verifies run on trees
    with FaultPlan().verify_exc(at=1):
        results = eng.run([(rep, 8), (rep, 8)], results=True)
    assert results[0].status == "failed"
    assert results[1].ok
    np.testing.assert_array_equal(results[1].tokens, gold)
    assert eng.audit() == []
    assert all(n.refcount == 0 for n in eng.prefix.walk())


def test_tree_verify_nan_logits_guarded(ctx4):
    """Non-finite logits in a tree-verify chunk fail that request with
    a structured ``nan_logits`` — never argmax'd into accepted tokens,
    and never a poisoned pool."""
    from triton_distributed_tpu.runtime.faults import FaultPlan

    model, eng = _tree_engine(ctx4)
    rep = np.asarray(MOTIF * 4, np.int32)
    gold = golden(model, list(rep), 8)
    eng.run([(rep, 8)])

    def nanify(value, _ctx):
        value = np.array(value, np.float32)
        value[0] = np.nan
        return value

    with FaultPlan().on("spec.logits", at=1, mutate=nanify):
        results = eng.run([(rep, 8), (rep, 8)], results=True)
    assert results[0].status == "nan_logits"
    assert results[1].ok
    np.testing.assert_array_equal(results[1].tokens, gold)
    assert eng.last_stats["nonfinite_logits"] == 1
    assert eng.audit() == []


# -- observability ---------------------------------------------------------


def test_tree_metrics_exposed_on_the_wire(ctx4):
    """Acceptance (ISSUE 16): the tree counters, the ``tdt_spec_*``
    counter aliases for the draft/rollback ledger, and the accept-rate
    gauge all surface through ``{"cmd": "metrics"}``."""
    from triton_distributed_tpu.serving.server import ModelServer, request

    _model, eng = _tree_engine(ctx4, max_batch=2)
    server = ModelServer(eng).start()
    try:
        prompt = (MOTIF * 4)
        for _ in range(2):  # second pass drafts from the warm radix
            r = request(server.host, server.port,
                        {"requests": [prompt], "gen_lens": [8]})
            assert r["results"][0]["status"] == "ok"
        m = request(server.host, server.port, {"cmd": "metrics"})
        snap = m["metrics"]
        for name in ("tdt_spec_tree_rounds_total",
                     "tdt_spec_tree_nodes_total",
                     "tdt_spec_tree_depth_total",
                     "tdt_spec_tree_branch_accepts_total",
                     "tdt_spec_draft_tokens_total",
                     "tdt_spec_rollback_tokens_total"):
            assert name in m["prometheus"], name
            assert snap[name]["type"] == "counter", name
        st = eng.last_stats
        series = snap["tdt_spec_draft_tokens_total"]["series"]
        assert series and series[0]["value"] >= st["spec_draft_tokens"]
        gauge = snap["tdt_spec_accept_rate"]
        assert gauge["type"] == "gauge"
        rate = gauge["series"][0]["value"]
        assert 0.0 <= rate <= 1.0
        # The trace ring carries the tree-verify spans.
        ev = request(server.host, server.port, {"cmd": "events", "since": 0})
        assert any(e["kind"] == "spec_verify" for e in ev["events"])
    finally:
        request(server.host, server.port, {"cmd": "shutdown"})
        server.shutdown()


# -- loadgen: the agentic continuation class ------------------------------


def test_loadgen_agentic_class_and_trace_compat():
    """The seeded ``"agentic"`` class reshapes its requests into
    prefix+motif×repeats continuations (the shape tree drafting feeds
    on) while every OTHER row — and every spec without the class — is
    bit-identical to the pre-agentic generator."""
    from perf.loadgen import LoadSpec, generate_trace

    base = LoadSpec(n_requests=24, seed=3)
    mixed = dataclasses.replace(
        base, class_mix=(("interactive", 2.0), ("agentic", 1.0)),
        agentic_motif=5, agentic_repeats=3,
    )
    plain, agentic = generate_trace(base), generate_trace(mixed)
    # Mix-less spec: trace unchanged by the feature landing at all.
    assert plain == generate_trace(LoadSpec(n_requests=24, seed=3))
    ag_rows = [r for r in agentic if r["slo_class"] == "agentic"]
    assert ag_rows, "mix produced no agentic rows at this seed"
    prefix_len = base.prefix_len
    motifs = {}
    for row, old in zip(agentic, plain):
        assert row["t"] == old["t"] and row["prefix_id"] == old["prefix_id"]
        if row["slo_class"] != "agentic":
            # Non-agentic rows keep the exact pre-mix prompt.
            assert row["prompt"] == old["prompt"]
            continue
        prefix = row["prompt"][:prefix_len]
        assert prefix == old["prompt"][:prefix_len]
        tail = row["prompt"][prefix_len:]
        assert len(tail) == 5 * 3
        assert tail == tail[:5] * 3  # the motif repeats verbatim
        motifs.setdefault(row["prefix_id"], tail[:5])
        # One motif PER PREFIX: shared across requests → radix reuse.
        assert motifs[row["prefix_id"]] == tail[:5]
    # A mix WITHOUT the agentic class leaves prompts untouched too.
    other = generate_trace(dataclasses.replace(
        base, class_mix=(("interactive", 1.0), ("batch", 1.0))
    ))
    assert [r["prompt"] for r in other] == [r["prompt"] for r in plain]
