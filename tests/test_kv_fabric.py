"""KV fabric tests (docs/scale-out.md "KV fabric").

Layers of evidence:

- pure store/client semantics — ``PageStore.digest()`` memoization and
  invalidation, ``tier_digest_match_len`` page walks, and the
  ``FabricClient``'s bounded degradation (dead peers, hung peers past
  the deadline, refused probes with cooldown) — milliseconds, no model;
- the wire serve side: ``tier_probe``/``tier_get`` verbs on a live
  ``ModelServer`` answering digest-keyed probes and serving the
  store's checksummed bytes verbatim, with every malformed request
  refused as ``bad_request``;
- engine-level peer fault-back on the tiny model: a local tier miss
  pulled from a PEER replica's tier (in-process and over the wire)
  with outputs bit-exact vs tier-less goldens, and the acceptance
  contract that a remote entry can NEVER produce wrong bits —
  checksum-tamper, stale-geometry, and foreign-fingerprint entries all
  degrade to re-prefill through the UNCHANGED PR 12 validation path;
- placement: the router's tier-affinity decision and the pools decode
  score's tier term; warm boot from a shared disk tier; fleet-scope
  metric merging of the ``tdt_tier_*``/``tdt_fabric_*`` families.
"""

import socket
import time

import numpy as np
import pytest

import jax

from triton_distributed_tpu.models import AutoLLM, kv_tier
from triton_distributed_tpu.models.kv_tier import (
    PREFIX_KIND,
    SNAP_KIND,
    FabricClient,
    LocalFabricPeer,
    PageStore,
    WireFabricPeer,
    chain_digest,
    tier_digest_match_len,
)
from triton_distributed_tpu.runtime import mesh as mesh_mod
from triton_distributed_tpu.runtime.faults import FaultPlan


@pytest.fixture(scope="module")
def fabric_model():
    """ONE tiny model (and mesh) for the whole module — the
    test_router.py convention: compiled programs cache per model
    instance and every engine here shares the same shapes."""
    ctx = mesh_mod.initialize_distributed(tp=4, devices=jax.devices()[:4])
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    yield model
    mesh_mod.finalize_distributed()


MK = dict(max_batch=1, page_size=16, max_length=64, prefix_cache=True)


def _mk_reqs(rng, n=2, prefix_tokens=32, tail=4, gen=3):
    reqs = []
    for _ in range(n):
        pre = rng.integers(1, 200, size=prefix_tokens).astype(np.int32)
        t = rng.integers(1, 200, size=tail).astype(np.int32)
        reqs.append((np.concatenate([pre, t]), gen))
    return reqs


def _spill_engine(model, r1, **kw):
    """A tight-pool engine that has served ``r1`` and then a 4-page
    evictor prompt — r1's WHOLE chain (both full pages) now lives in
    its TIER, not its radix tree. A 3-page evictor is not enough: LRU
    spills the leaf only, and a peer's contiguous fault-back walk
    would break at the still-tree-resident first page."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    evict = _mk_reqs(np.random.default_rng(987), n=1, prefix_tokens=48)[0]
    eng = ContinuousEngine(
        model, num_pages=4, tier_bytes=32 << 20, **MK, **kw
    )
    eng.run([r1])
    eng.run([evict])
    toks = [int(t) for t in r1[0]]
    assert eng.tier.contains(PREFIX_KIND, chain_digest(toks[:16]))
    assert eng.tier.contains(PREFIX_KIND, chain_digest(toks[:32]))
    return eng


# -- pure: digest, match walk, client degradation --------------------------


def test_pagestore_digest_summary_and_memoization():
    """``digest()`` summarizes RAM-resident prefix chains (truncated
    keys, per-kind counts, a set hash) and is memoized on the mutation
    counter: unchanged stores return the SAME object, every mutation
    class (put/delete/clear) invalidates it."""
    s = PageStore(capacity_bytes=1 << 20)
    d0 = s.digest()
    assert d0["chains"] == [] and d0["counts"] == {}
    assert s.digest() is d0  # memoized while untouched

    k1 = chain_digest([1, 2, 3])
    k2 = chain_digest([9, 8, 7])
    assert s.put(PREFIX_KIND, k1, {"chain": [1, 2, 3]})
    d1 = s.digest()
    assert d1 is not d0 and d1["hash"] != d0["hash"]
    assert d1["chains"] == [k1[:16]]
    assert d1["counts"] == {PREFIX_KIND: 1}
    assert s.digest() is d1

    assert s.put(PREFIX_KIND, k2, {"chain": [9, 8, 7]})
    assert s.put(SNAP_KIND, "t1", {"out": [1]})
    d2 = s.digest()
    assert d2["chains"] == sorted([k1[:16], k2[:16]])
    assert d2["counts"] == {PREFIX_KIND: 2, SNAP_KIND: 1}
    assert "t1"[:16] not in d2["chains"]  # snap entries never listed

    s.delete(PREFIX_KIND, k1)
    d3 = s.digest()
    assert d3["chains"] == [k2[:16]] and d3["hash"] != d2["hash"]
    s.clear()
    assert s.digest()["chains"] == []


def test_tier_digest_match_len():
    """Whole-page walk against a published digest: contiguous pages
    from the root count, the first absent page stops the walk, at
    least one token is always left to prefill, and malformed digests
    read as 0 (placement falls back to radix affinity)."""
    toks = list(range(1, 40))  # 39 tokens, ps=16 → pages at 16, 32
    full = {
        "ps": 16,
        "chains": [chain_digest(toks[:16])[:16],
                   chain_digest(toks[:32])[:16]],
    }
    assert tier_digest_match_len(full, toks) == 32
    first_only = {"ps": 16, "chains": [chain_digest(toks[:16])[:16]]}
    assert tier_digest_match_len(first_only, toks) == 16
    # Second page present but FIRST absent: contiguity is required.
    second_only = {"ps": 16, "chains": [chain_digest(toks[:32])[:16]]}
    assert tier_digest_match_len(second_only, toks) == 0
    # A fully-covered prompt still leaves one token to prefill.
    assert tier_digest_match_len(full, toks[:32]) == 16
    # Malformed/missing digests degrade to 0, never raise.
    assert tier_digest_match_len(None, toks) == 0
    assert tier_digest_match_len({}, toks) == 0
    assert tier_digest_match_len({"ps": 0, "chains": ["x"]}, toks) == 0
    assert tier_digest_match_len({"ps": "no", "chains": ["x"]}, toks) == 0
    assert tier_digest_match_len({"ps": 16, "chains": []}, toks) == 0
    assert tier_digest_match_len({"ps": 16}, toks) == 0


def test_fabric_client_fetch_and_degradation():
    """Pure client semantics: a fetch returns the peer entry DECODED
    (the codec is the transport); a dead wire peer, a refused probe
    (with cooldown), and a hung pull past the deadline all degrade to
    None without wedging — and every failure is counted."""
    store = PageStore(capacity_bytes=1 << 20)
    key = chain_digest([4, 5, 6])
    payload = {"chain": [4, 5, 6], "page_size": 16}
    assert store.put(PREFIX_KIND, key, payload)

    fc = FabricClient(pull_timeout_s=5.0, cooldown_s=60.0)
    assert fc.fetch(PREFIX_KIND, key) is None  # peerless: inert
    fc.set_peers([LocalFabricPeer("a", store)])
    assert fc.fetch(PREFIX_KIND, key) == payload
    assert fc.fetch(PREFIX_KIND, "absent-key") is None  # fleet miss
    assert fc.stats["remote_hits"] == 1
    assert fc.stats["pull_bytes"] > 0

    # Dead wire peer: the connect refuses, the fetch degrades, the
    # peer cools down (the second fetch never re-probes it).
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    dead_port = sock.getsockname()[1]
    sock.close()
    fc2 = FabricClient(pull_timeout_s=2.0, cooldown_s=60.0)
    fc2.set_wire_peers([
        {"name": "dead", "host": "127.0.0.1", "port": dead_port},
        {"junk": True},  # malformed row: skipped, not fatal
    ])
    assert len(fc2.peers) == 1
    assert fc2.fetch(PREFIX_KIND, key) is None
    assert fc2.stats["pull_failures"] == 1
    probes = fc2.stats["probes"]
    assert fc2.fetch(PREFIX_KIND, key) is None  # cooled: skipped
    assert fc2.stats["probes"] == probes

    # Refused probe cools the peer the same way.
    fc3 = FabricClient(pull_timeout_s=2.0, cooldown_s=60.0)
    fc3.set_peers([LocalFabricPeer("a", store)])
    with FaultPlan(seed=1).refuse_fabric(op="probe") as plan:
        assert fc3.fetch(PREFIX_KIND, key) is None
    assert plan.fired and fc3.stats["pull_failures"] == 1
    assert fc3.fetch(PREFIX_KIND, key) is None  # still cooling

    # Hung pull: valid bytes arriving PAST the deadline are dropped —
    # honoring them would make the timeout advisory.
    fc4 = FabricClient(pull_timeout_s=0.05, cooldown_s=0.0)
    fc4.set_peers([LocalFabricPeer("a", store)])
    with FaultPlan(seed=1).slow_fabric(0.2) as plan:
        t0 = time.monotonic()
        assert fc4.fetch(PREFIX_KIND, key) is None
    assert plan.fired and time.monotonic() - t0 < 2.0
    assert fc4.stats["remote_hits"] == 0
    assert fc4.stats["pull_failures"] >= 1
    assert fc4.fetch(PREFIX_KIND, key) == payload  # healthy again


def test_pools_decode_score_tier_term():
    """Only tier coverage BEYOND the radix match scores (pages the
    radix holds would never fault back), at TIER_MATCH_WEIGHT — a
    pure-tier full match exactly offsets full occupancy, and a radix
    match still beats a tier match of the same length."""
    from triton_distributed_tpu.serving import pools

    class Rep:
        pending = 0
        max_pending = 8
        free_pages = 0

    r = Rep()
    base = pools.decode_score(r, 0, 32)
    assert pools.decode_score(r, 0, 32, tier_matched=32) == pytest.approx(
        base + pools.TIER_MATCH_WEIGHT
    )
    # Tier coverage the radix already has adds nothing.
    assert pools.decode_score(r, 16, 32, tier_matched=16) == \
        pools.decode_score(r, 16, 32)
    assert pools.decode_score(r, 16, 32, tier_matched=8) == \
        pools.decode_score(r, 16, 32)
    # Radix outranks tier at equal coverage.
    assert pools.decode_score(r, 32, 32) > \
        pools.decode_score(r, 0, 32, tier_matched=32)
    # A saturated replica with a pure-tier full match scores 0 — even
    # with an idle cold one (score 0): tier wins only with headroom.
    sat = Rep()
    sat.pending = 8
    assert pools.decode_score(sat, 0, 32, tier_matched=32) == \
        pytest.approx(0.0)


def test_fleet_scope_tier_fabric_metrics_merge():
    """Satellite (e): merging per-replica expositions keeps each
    child's tdt_tier_*/tdt_fabric_* series intact under its replica
    label — summing across replicas IS the fleet total."""
    from triton_distributed_tpu.obs.metrics import (
        Registry,
        merge_expositions,
        prometheus_text,
    )

    regs = {"r0": Registry(), "r1": Registry()}
    vals = {"r0": {"tdt_tier_hits_total": 3,
                   "tdt_fabric_remote_hits_total": 2,
                   "tdt_fabric_pull_bytes_total": 512,
                   "tdt_tier_remote_pages_total": 2},
            "r1": {"tdt_tier_hits_total": 5,
                   "tdt_fabric_remote_hits_total": 0,
                   "tdt_fabric_pull_bytes_total": 0,
                   "tdt_tier_remote_pages_total": 0}}
    for name, reg in regs.items():
        for metric, v in vals[name].items():
            reg.counter(metric, "test").inc(v)
    merged = merge_expositions(
        {name: prometheus_text(reg) for name, reg in regs.items()},
        label="replica",
    )
    series = {}
    for line in merged.splitlines():
        if line and not line.startswith("#"):
            k, v = line.rsplit(" ", 1)
            series[k] = float(v)
    for name in regs:
        for metric, v in vals[name].items():
            assert series[f'{metric}{{replica="{name}"}}'] == v
    for metric in vals["r0"]:
        total = sum(v for k, v in series.items() if k.startswith(metric))
        assert total == vals["r0"][metric] + vals["r1"][metric]


# -- wire verbs ------------------------------------------------------------


def test_wire_tier_verbs(fabric_model):
    """``tier_probe`` answers digest membership without touching the
    store's stats/LRU; ``tier_get`` serves the store's wire bytes
    VERBATIM; malformed requests, foreign kinds, and tier-less engines
    all refuse as ``bad_request``."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.serving.server import ModelServer, request

    rng = np.random.default_rng(11)
    [r1] = _mk_reqs(rng, n=1)
    eng = _spill_engine(fabric_model, r1)
    keys = [k for k in eng.tier.keys(PREFIX_KIND)]
    assert keys
    hits_before = eng.tier.stats["hits"]
    srv = ModelServer(eng).start()
    try:
        resp = request(srv.host, srv.port,
                       {"cmd": "tier_probe", "keys": keys + ["absent"]})
        assert resp["have"] == [True] * len(keys) + [False]
        assert eng.tier.stats["hits"] == hits_before  # no LRU/stat touch

        got = request(srv.host, srv.port,
                      {"cmd": "tier_get", "key": keys[0]})
        assert got["found"]
        import base64

        blob = base64.b64decode(got["blob"], validate=True)
        assert blob == eng.tier.get_blob(PREFIX_KIND, keys[0])
        # The served bytes decode through the PR 12 codec under the
        # SAME key — the codec is the transport.
        payload = kv_tier._decode(PREFIX_KIND, keys[0], blob)
        assert chain_digest(payload["chain"]) == keys[0]
        miss = request(srv.host, srv.port,
                       {"cmd": "tier_get", "key": "absent"})
        assert miss == {"found": False}

        for bad in (
            {"cmd": "tier_probe"},  # no keys
            {"cmd": "tier_probe", "keys": []},
            {"cmd": "tier_probe", "keys": [1, 2]},
            {"cmd": "tier_probe", "keys": ["k"] * 257},  # over bound
            {"cmd": "tier_probe", "keys": ["k"], "kind": "snap"},
            {"cmd": "tier_get"},  # no key
            {"cmd": "tier_get", "key": keys[0], "kind": "snap"},
            {"cmd": "tier_peers", "peers": "not-a-list"},
        ):
            with pytest.raises(RuntimeError, match="bad_request"):
                request(srv.host, srv.port, bad)
    finally:
        request(srv.host, srv.port, {"cmd": "shutdown"}, timeout=10.0)
        srv.shutdown()

    # A tier-less engine refuses the whole verb family by name.
    bare = ContinuousEngine(fabric_model, **MK)
    srv2 = ModelServer(bare).start()
    try:
        with pytest.raises(RuntimeError, match="bad_request.*tier"):
            request(srv2.host, srv2.port,
                    {"cmd": "tier_probe", "keys": ["k"]})
        with pytest.raises(RuntimeError, match="bad_request"):
            request(srv2.host, srv2.port,
                    {"cmd": "tier_peers", "peers": []})
    finally:
        request(srv2.host, srv2.port, {"cmd": "shutdown"}, timeout=10.0)
        srv2.shutdown()


# -- engine: peer fault-back, containment ----------------------------------


def test_fabric_local_miss_remote_hit_bitexact(fabric_model,
                                               fresh_telemetry):
    """The tentpole in-process: engine B's LOCAL tier is cold, its
    peer's tier holds the chain — admission pulls it through the
    fabric, grafts it, and the output is bit-exact vs a tier-less
    golden. The validated entry is ADOPTED into B's tier."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.obs import metrics as obs_metrics

    rng = np.random.default_rng(21)
    [r1] = _mk_reqs(rng, n=1)
    gold = ContinuousEngine(fabric_model, **MK).run([r1])[0]
    a = _spill_engine(fabric_model, r1)

    fc = FabricClient()
    fc.set_peers([LocalFabricPeer("a", a.tier)])
    b = ContinuousEngine(
        fabric_model, tier_bytes=32 << 20, fabric=fc, **MK
    )
    assert not b.tier.may_contain(PREFIX_KIND)  # cold local tier
    np.testing.assert_array_equal(b.run([r1])[0], gold)
    st = b.last_stats
    assert st["tier_remote_pages"] >= 1
    assert st["tier_hits"] >= 1
    assert st["fabric"]["remote_hits"] >= 1
    assert st["prefill_tokens"] < len(r1[0])  # beat re-prefill
    # Adoption: the pulled entries now answer locally (and to peers).
    assert b.tier.may_contain(PREFIX_KIND)
    assert any(b.tier.contains(PREFIX_KIND, k)
               for k in a.tier.keys(PREFIX_KIND))
    kinds = [e.kind for e in obs_events.default_ring().tail(0)[0]]
    assert "fabric_pull" in kinds
    snap = obs_metrics.default_registry().snapshot()
    assert snap["tdt_fabric_remote_hits_total"]["series"][0]["value"] >= 1
    assert snap["tdt_tier_remote_pages_total"]["series"][0]["value"] >= 1
    assert a.audit() == [] and b.audit() == []


def test_fabric_wire_pull_bitexact(fabric_model):
    """The same pull over the WIRE: peer A behind a live ModelServer,
    B's client wired by tier_peers dicts — first batch on a cold B is
    bit-exact with remote pages faulted through tier_probe/tier_get."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.serving.server import ModelServer, request

    rng = np.random.default_rng(31)
    [r1] = _mk_reqs(rng, n=1)
    gold = ContinuousEngine(fabric_model, **MK).run([r1])[0]
    a = _spill_engine(fabric_model, r1)
    srv = ModelServer(a).start()
    try:
        fc = FabricClient(pull_timeout_s=5.0)
        b = ContinuousEngine(
            fabric_model, tier_bytes=32 << 20, fabric=fc, **MK
        )
        # Wire the peer table THROUGH the verb (the supervisor
        # broadcast path) against B's own server.
        srv_b = ModelServer(b).start()
        try:
            resp = request(srv_b.host, srv_b.port, {
                "cmd": "tier_peers",
                "peers": [{"name": "a", "host": srv.host,
                           "port": srv.port}],
            })
            assert resp == {"ok": True, "peers": 1}
            out = request(srv_b.host, srv_b.port, {
                "requests": [np.asarray(r1[0]).tolist()],
                "gen_lens": [r1[1]],
            })
            np.testing.assert_array_equal(out["outputs"][0], gold)
            assert out["stats"]["tier_remote_pages"] >= 1
            assert out["stats"]["fabric"]["remote_hits"] >= 1
        finally:
            request(srv_b.host, srv_b.port, {"cmd": "shutdown"},
                    timeout=10.0)
            srv_b.shutdown()
    finally:
        request(srv.host, srv.port, {"cmd": "shutdown"}, timeout=10.0)
        srv.shutdown()
    assert a.audit() == [] and b.audit() == []


def test_fabric_corrupt_remote_degrades_bitexact(fabric_model):
    """Chaos: a garbled remote entry dies at the client's CRC check —
    the SAME containment boundary a corrupt local entry crosses — and
    the admission re-prefills bit-exactly. No remote page lands."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    rng = np.random.default_rng(41)
    [r1] = _mk_reqs(rng, n=1)
    gold = ContinuousEngine(fabric_model, **MK).run([r1])[0]
    a = _spill_engine(fabric_model, r1)
    keys_before = set(a.tier.keys(PREFIX_KIND))

    fc = FabricClient()
    fc.set_peers([LocalFabricPeer("a", a.tier)])
    b = ContinuousEngine(
        fabric_model, tier_bytes=32 << 20, fabric=fc, **MK
    )
    with FaultPlan(seed=1).corrupt_fabric(times=8) as plan:
        np.testing.assert_array_equal(b.run([r1])[0], gold)
    assert plan.fired
    st = b.last_stats
    assert st["tier_remote_pages"] == 0
    assert st["fabric"]["pull_failures"] >= 1
    assert st["prefill_tokens"] >= len(r1[0]) - MK["page_size"]
    # The PEER's entry is untouched (nothing local to delete, and the
    # fabric never deletes remotely) — the fault was in transit.
    assert set(a.tier.keys(PREFIX_KIND)) == keys_before
    assert a.audit() == [] and b.audit() == []


def test_fabric_hung_and_dead_peer_not_blocking(fabric_model):
    """A hung peer trips the fetch deadline (late valid bytes are
    discarded) and a dead peer degrades to the local-miss path —
    admission completes bit-exactly either way, promptly."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    rng = np.random.default_rng(51)
    [r1] = _mk_reqs(rng, n=1)
    gold = ContinuousEngine(fabric_model, **MK).run([r1])[0]
    a = _spill_engine(fabric_model, r1)

    fc = FabricClient(pull_timeout_s=0.05, cooldown_s=60.0)
    fc.set_peers([LocalFabricPeer("a", a.tier)])
    b = ContinuousEngine(
        fabric_model, tier_bytes=32 << 20, fabric=fc, **MK
    )
    with FaultPlan(seed=1).slow_fabric(0.3, times=8) as plan:
        t0 = time.monotonic()
        np.testing.assert_array_equal(b.run([r1])[0], gold)
    assert plan.fired
    assert time.monotonic() - t0 < 30.0  # stalled pulls never pile up
    assert b.last_stats["tier_remote_pages"] == 0
    assert b.last_stats["fabric"]["pull_failures"] >= 1

    # Dead peer (nothing listening): connect refuses, the peer cools
    # down, the run degrades to plain re-prefill.
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    port = sock.getsockname()[1]
    sock.close()
    fc2 = FabricClient(pull_timeout_s=0.5, cooldown_s=60.0)
    fc2.set_peers([WireFabricPeer("dead", "127.0.0.1", port)])
    c = ContinuousEngine(
        fabric_model, tier_bytes=32 << 20, fabric=fc2, **MK
    )
    np.testing.assert_array_equal(c.run([r1])[0], gold)
    assert c.last_stats["tier_remote_pages"] == 0
    assert fc2.stats["pull_failures"] >= 1
    assert a.audit() == [] and b.audit() == [] and c.audit() == []


def test_fabric_never_wrong_bits_matrix(fabric_model):
    """The acceptance contract: checksum-tampered, stale-geometry, and
    foreign-fingerprint peer entries ALL degrade to bit-exact
    re-prefill — the PR 12 validation path runs unchanged on remote
    payloads, and no fabric failure ever deletes the peer's entry."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    rng = np.random.default_rng(61)
    [r1] = _mk_reqs(rng, n=1)
    gold = ContinuousEngine(fabric_model, **MK).run([r1])[0]

    def cold_puller(peer_store):
        fc = FabricClient()
        fc.set_peers([LocalFabricPeer("a", peer_store)])
        return ContinuousEngine(
            fabric_model, tier_bytes=32 << 20, fabric=fc, **MK
        )

    # 1) checksum-tamper: flip a byte in every peer RAM blob.
    a1 = _spill_engine(fabric_model, r1)
    with a1.tier._lock:
        for k, blob in list(a1.tier._ram.items()):
            bb = bytearray(blob)
            bb[len(bb) // 2] ^= 0xFF
            a1.tier._ram[k] = bytes(bb)
    b1 = cold_puller(a1.tier)
    np.testing.assert_array_equal(b1.run([r1])[0], gold)
    assert b1.last_stats["tier_remote_pages"] == 0
    assert b1.fabric.stats["pull_failures"] >= 1
    a1.tier.clear()  # drop the hand-garbled blobs before the audit

    # 2) stale geometry: a peer entry spilled under page_size 8 does
    #    not key-match this engine's 16-token page chains at all —
    #    and a re-stamped wrong-geometry payload under the RIGHT key
    #    fails the engine's page_size check after a clean pull.
    a2 = _spill_engine(fabric_model, r1)
    for k in a2.tier.keys(PREFIX_KIND):
        payload = a2.tier.get(PREFIX_KIND, k)
        payload["page_size"] = 8
        assert a2.tier.put(PREFIX_KIND, k, payload)
    b2 = cold_puller(a2.tier)
    np.testing.assert_array_equal(b2.run([r1])[0], gold)
    assert b2.last_stats["tier_remote_pages"] == 0
    assert b2.fabric.stats["remote_hits"] >= 1  # pulled clean, THEN refused
    # The peer's entries survived the refusal (nothing local to delete).
    assert a2.tier.keys(PREFIX_KIND)

    # 3) foreign model fingerprint (a tier_dir outliving a checkpoint
    #    swap, served over the fabric): refused at the same check.
    a3 = _spill_engine(fabric_model, r1)
    for k in a3.tier.keys(PREFIX_KIND):
        payload = a3.tier.get(PREFIX_KIND, k)
        payload["model_fp"] = "other-weights"
        assert a3.tier.put(PREFIX_KIND, k, payload)
    b3 = cold_puller(a3.tier)
    np.testing.assert_array_equal(b3.run([r1])[0], gold)
    assert b3.last_stats["tier_remote_pages"] == 0
    for eng in (a1, b1, a2, b2, a3, b3):
        assert eng.audit() == []




# -- placement & warm boot -------------------------------------------------


def test_router_tier_affinity_placement(fabric_model):
    """The router scores TIER coverage alongside radix coverage: a
    prompt whose pages live only in a replica's tier routes back to
    that replica as ``tier_affinity`` (and faults back there) instead
    of landing least-loaded on a cold one."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.serving.router import Router

    rng = np.random.default_rng(71)
    [(p, gen)] = _mk_reqs(rng, n=1)
    gold = ContinuousEngine(fabric_model, **MK).run([(p, gen)])[0]

    # e0 serves p, then a 4-page prompt evicts p's chain to its TIER.
    e0 = _spill_engine(fabric_model, (p, gen))
    assert e0.tier.may_contain(PREFIX_KIND)
    toks = [int(t) for t in p]
    assert tier_digest_match_len(e0.tier_digest(), toks) >= 16
    e1 = ContinuousEngine(fabric_model, tier_bytes=32 << 20, **MK)

    router = Router([e0, e1])
    try:
        # The replicas' published tier digests steer the decision.
        r0 = next(r for r in router.replicas if r.engine is e0)
        assert r0.tier_match_len(toks) >= 16
        assert r0.match_len(toks) < r0.tier_match_len(toks)
        res = router.run([(p, gen)], results=True)
        assert res[0].status == "ok"
        np.testing.assert_array_equal(res[0].tokens, gold)
        st = router.last_stats["router"]
        assert st["tier_affinity_hits"] == 1
        assert st["tier_affinity_hit_tokens"] >= 16
        # It landed on e0 and faulted back from e0's LOCAL tier.
        assert e0.last_stats["tier_hits"] >= 1
        assert router.audit() == []
    finally:
        router.shutdown()


def test_warm_boot_from_shared_dir(fabric_model, tmp_path):
    """The scale-up arm in miniature: a FRESH engine over the pool's
    shared tier dir (the ``--tier-shared`` shape) serves its FIRST
    batch from the predecessors' spills — tier hits on batch one,
    bit-exact output."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    d = str(tmp_path / "fabric")
    rng = np.random.default_rng(81)
    [r1] = _mk_reqs(rng, n=1)
    gold = ContinuousEngine(fabric_model, **MK).run([r1])[0]
    a = _spill_engine(fabric_model, r1, tier_dir=d)  # whole chain on disk

    fresh = ContinuousEngine(
        fabric_model, tier_bytes=32 << 20, tier_dir=d, **MK
    )
    assert fresh.tier.may_contain(PREFIX_KIND)  # disk prescan: warm
    np.testing.assert_array_equal(fresh.run([r1])[0], gold)
    st = fresh.last_stats
    assert st["tier_hits"] >= 1 and st["tier_faults"] >= 1
    assert st["prefill_tokens"] < len(r1[0])  # warm boot beat re-prefill
    assert a.audit() == [] and fresh.audit() == []
