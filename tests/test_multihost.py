"""Multi-host fleet tests (docs/scale-out.md "Multi-host fleet"):
pluggable launchers, host failure domains, epoch fencing.

Layers of evidence:

- pure: launcher contracts (FakeHostLauncher bookkeeping, SSHLauncher
  argv rewriting and port assignment), the ``launcher.spawn`` fault
  seam, the supervisor's host ledger (rejoin refused by name, epochs
  monotonic across revive), spread-aware ``_pick_host``, and the CLI
  refusals — milliseconds, no processes;
- SSHLauncher's WIRE handshake with an empty command template (the
  child runs locally, the handshake is the real healthz poll): success
  path round-trips, and a child that never answers fails the spawn on
  OUR deadline, not the OS connect default;
- chaos (ISSUE-18 acceptance): SIGKILLing a whole fake host lands as
  exactly ONE ``host_down`` classification with parallel re-placement
  onto the survivor; a spawn-refused host drives spawn FAILOVER; and
  the SIGSTOP→thaw zombie path shows the epoch fence — the thawed
  host's late batch completions latch ZERO results.

Process tests spawn ``run_server --model stub`` children and
synchronize on conditions with deadlines, never bare sleeps.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from triton_distributed_tpu.models.stub import stub_generate
from triton_distributed_tpu.runtime.faults import FaultPlan
from triton_distributed_tpu.serving.launcher import (
    FakeHostLauncher,
    Launcher,
    LocalLauncher,
    SpawnError,
    SSHLauncher,
)


def _can_spawn() -> bool:
    try:
        return subprocess.run(
            [sys.executable, "-c", "pass"], timeout=60
        ).returncode == 0
    except Exception:  # noqa: BLE001 — any failure means "cannot"
        return False


_SPAWN_OK = _can_spawn()
needs_procs = pytest.mark.skipif(
    not _SPAWN_OK or not hasattr(signal, "SIGKILL"),
    reason="child-process spawning unavailable on this platform",
)

PROMPTS = [
    np.arange(1, 9, dtype=np.int32),
    np.arange(20, 30, dtype=np.int32),
    np.arange(40, 46, dtype=np.int32),
]
GENS = [5, 4, 3]
GOLDS = [stub_generate(p, g) for p, g in zip(PROMPTS, GENS)]


def _stub_specs(n, delay_s=0.4, hosts=None):
    from triton_distributed_tpu.serving.supervisor import stub_spec

    specs = [
        stub_spec(f"r{i}", delay_s=delay_s, page_size=4, num_pages=64)
        for i in range(n)
    ]
    if hosts:
        for i, s in enumerate(specs):
            s.host = hosts[i % len(hosts)]
    return specs


# -- pure: launcher contracts, seam, ledger, placement, CLI --------------


def test_launcher_contract_and_fake_host_bookkeeping():
    """The seam's base contract (no host notion → host machinery
    dormant) and FakeHostLauncher's ledger: named hosts, down-marking,
    and spawn refusal BEFORE any process work when the target (or
    every) host is down."""
    base = Launcher()
    assert base.hosts() == [] and base.host_up("anything")
    base.reap()  # no-op, never raises
    with pytest.raises(NotImplementedError):
        base.spawn(object())
    # LocalLauncher reports no hosts: a supervisor over it keeps every
    # host-domain feature dormant (the byte-identical default path).
    assert LocalLauncher().hosts() == []

    laun = FakeHostLauncher(("h0", "h1"))
    assert laun.hosts() == ["h0", "h1"]
    assert laun.host_up("h0") and not laun.host_up("nope")
    laun.set_down("h1")
    assert not laun.host_up("h1")
    spec = _stub_specs(1)[0]
    spec.host = "h1"
    with pytest.raises(SpawnError, match="fake host h1 is down"):
        laun.spawn(spec)
    spec.host = "hX"
    with pytest.raises(SpawnError, match="unknown fake host"):
        laun.spawn(spec)
    laun.set_down("h0")
    spec.host = None
    with pytest.raises(SpawnError, match="every fake host is down"):
        laun.spawn(spec)
    with pytest.raises(ValueError):
        FakeHostLauncher(())
    # kill/hang/thaw on an empty host: zero groups hit, no exception.
    assert FakeHostLauncher(("h0",)).kill_host("h0") == 0


def test_ssh_launcher_argv_and_port_assignment():
    """The launcher owns the port (a child binding :0 remotely cannot
    report back) and rewrites the child argv for routable addressing:
    ``--port`` pinned, ``--host 0.0.0.0``, ``--advertise-host`` the
    placement host, ``spec.env`` as env-prefix tokens."""
    from triton_distributed_tpu.serving.supervisor import ReplicaSpec

    laun = SSHLauncher(["ha", "hb"], port_base=50000)
    spec = ReplicaSpec("r0", ["x", "--port", "0"],
                       env={"JAX_PLATFORMS": "cpu"})
    spec.host = "hb"
    host, port = laun._alloc(spec)
    assert (host, port) == ("hb", 50000)
    argv = SSHLauncher._child_argv(spec, port, host)
    assert argv[:2] == ["env", "JAX_PLATFORMS=cpu"]
    i = argv.index("--port")
    assert argv[i + 1] == "50000"
    assert argv[argv.index("--host") + 1] == "0.0.0.0"
    assert argv[argv.index("--advertise-host") + 1] == "hb"
    # Pre-set --host / --advertise-host are respected, --port appended
    # when absent.
    spec2 = ReplicaSpec("r1", ["x", "--host", "10.0.0.9"])
    argv2 = SSHLauncher._child_argv(spec2, 50001, "ha")
    assert argv2[argv2.index("--host") + 1] == "10.0.0.9"
    assert argv2[argv2.index("--port") + 1] == "50001"
    # Hostless specs fall back least-spawned; ports stay monotonic.
    spec2.host = None
    host2, port2 = laun._alloc(spec2)
    assert host2 == "ha" and port2 == 50001
    with pytest.raises(ValueError):
        SSHLauncher([])


def test_refuse_spawn_seam_units():
    """``FaultPlan.refuse_spawn`` arms the ``launcher.spawn`` seam:
    the gate surfaces it as SpawnError (the supervisor's failover
    type), and ``host=`` narrows the blast radius."""
    from triton_distributed_tpu.serving.launcher import _spawn_gate

    with FaultPlan(seed=1).refuse_spawn(host="h1", times=2) as plan:
        _spawn_gate("r0", "h0")  # wrong host: not matched
        with pytest.raises(SpawnError, match="spawn refused on host h1"):
            _spawn_gate("r1", "h1")
        assert plan.fired and plan.fired[0][0] == "launcher.spawn"
    with FaultPlan(seed=1).refuse_spawn(replica="rZ") as plan:
        _spawn_gate("r0", None)  # wrong replica: not matched
        with pytest.raises(SpawnError):
            _spawn_gate("rZ", None)


def test_host_ledger_rejoin_refused_and_epoch_monotonic():
    """The supervisor's host ledger: a down host refuses spawns BY
    NAME (the zombie-rejoin gate), revive reopens placement but the
    fence epoch stays bumped — a revive can never un-fence results
    from the dead generation."""
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    laun = FakeHostLauncher(("h0", "h1"))
    sup = FleetSupervisor(
        _stub_specs(2, hosts=["h0", "h1"]), launcher=laun,
    )
    assert set(sup.host_stats()) == {"h0", "h1"}
    sup.mark_host_down("h1")
    st = sup.host_stats()["h1"]
    assert st["down"] and st["epoch"] == 1
    slot = next(s for s in sup._slots if s.spec.host == "h1")
    with pytest.raises(SpawnError, match="host h1 is marked down"):
        sup._spawn(slot)
    # Placement refuses it too.
    assert sup._pick_host() == "h0"
    sup.revive_host("h1")
    st = sup.host_stats()["h1"]
    assert not st["down"] and st["epoch"] == 1  # epoch survives revive
    sup.mark_host_down("h1")
    assert sup.host_stats()["h1"]["epoch"] == 2  # strictly monotonic
    # Idempotent: re-marking a down host does not re-bump.
    sup.mark_host_down("h1")
    assert sup.host_stats()["h1"]["epoch"] == 2


def test_pick_host_spreads_roles_across_up_hosts():
    """Spread-aware placement: the next slot of a role lands on the
    host carrying the fewest of that role (ties: fewest total, then
    name), never on a down host; no up host → None."""
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    laun = FakeHostLauncher(("h0", "h1"))
    specs = _stub_specs(3, hosts=["h0", "h0", "h1"])
    specs[2].role = "decode"
    sup = FleetSupervisor(specs, launcher=laun)
    # h0 has 2 mixed, h1 has 1 decode → mixed placement prefers h1.
    assert sup._pick_host(role="mixed") == "h1"
    # decode placement prefers h0 (zero decode slots there).
    assert sup._pick_host(role="decode") == "h0"
    assert sup._pick_host(role="mixed", exclude={"h1"}) == "h0"
    sup.mark_host_down("h1")
    assert sup._pick_host(role="mixed") == "h0"
    sup.mark_host_down("h0")
    assert sup._pick_host(role="mixed") is None


def test_cli_refusals_multihost():
    """run_server refuses the multi-host misuses BY FLAG NAME before
    anything boots: a shared tier dir cannot cross hosts, rival
    launchers cannot combine, and host flags need a fleet shape."""
    from triton_distributed_tpu.serving.run_server import main

    for argv in (
        ["--model", "tiny", "--fleet", "2", "--fake-hosts", "2",
         "--tier-shared", "--tier-dir", "/tmp/x"],
        ["--model", "tiny", "--fleet", "2", "--hosts", "a,b",
         "--tier-shared", "--tier-dir", "/tmp/x"],
        ["--model", "stub", "--fleet", "2", "--hosts", "a,b",
         "--fake-hosts", "2"],
        ["--model", "stub", "--fake-hosts", "2"],
        ["--model", "stub", "--hosts", "a,b"],
    ):
        with pytest.raises(SystemExit) as exc:
            main(argv)
        assert exc.value.code == 2, argv


# -- SSH launcher: the wire handshake, no ssh needed ---------------------


@needs_procs
def test_ssh_wire_handshake_success_and_bounded_timeout():
    """An empty command template runs the child locally, so this is
    the REAL healthz-poll handshake: the launcher-assigned port comes
    up serving, and a child that never answers fails the spawn within
    the deadline (plus kill/reap), not the OS connect default."""
    import socket

    from triton_distributed_tpu.serving.supervisor import ReplicaSpec

    # Grab a free port for the launcher to assign deterministically.
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port_base = s.getsockname()[1]
    s.close()
    laun = SSHLauncher(["127.0.0.1"], cmd_template=(),
                       port_base=port_base)
    spec = _stub_specs(1, delay_s=0.0)[0]
    spec.host = "127.0.0.1"
    rep = laun.spawn(spec, spawn_timeout_s=120.0)
    try:
        assert rep.healthz() == {"ok": True, "state": "serving"}
        assert rep.host_tag == "127.0.0.1"
        assert rep.proc.poll() is None
    finally:
        rep.proc.kill()
        rep.proc.wait(timeout=10)

    # Never-answering child: the handshake fails on OUR deadline.
    mute = ReplicaSpec(
        "mute", [sys.executable, "-c", "import time; time.sleep(60)"]
    )
    mute.host = "127.0.0.1"
    t0 = time.monotonic()
    with pytest.raises(SpawnError, match="never answered healthz"):
        laun.spawn(mute, spawn_timeout_s=1.0)
    assert time.monotonic() - t0 < 15.0


# -- chaos: whole-host loss, failover, zombie fence ----------------------


@needs_procs
def test_kill_host_single_host_down_and_parallel_replace(fresh_telemetry):
    """ISSUE-18 acceptance core: SIGKILLing every process on a fake
    host lands as exactly ONE ``host_down`` event (correlated
    classification, not N independent timeouts), every lost slot is
    re-placed on the survivor (spawn failover events + counter), and
    the recovered fleet serves bit-exact."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    laun = FakeHostLauncher(("h0", "h1"))
    sup = FleetSupervisor(
        _stub_specs(4, delay_s=0.05, hosts=["h0", "h1"]),
        launcher=laun, heartbeat_s=0.1, heartbeat_timeout_s=1.0,
        heartbeat_misses=2, respawn_backoff_s=0.2,
        spawn_timeout_s=120.0,
    )
    try:
        router = sup.start()
        assert sup.host_stats()["h1"]["slots"] == ["r1", "r3"]
        # Raw SIGKILL of every process group on h1 WITHOUT telling the
        # launcher: the supervisor must classify the correlated loss
        # from sibling corroboration alone (the production shape — a
        # dead machine does not announce itself).
        assert laun._signal_host("h1", signal.SIGKILL) == 2
        assert sup.wait_for(
            lambda: sup.host_stats()["h1"]["down"], timeout_s=30
        ), sup.stats()
        assert sup.wait_healthy(4, timeout_s=60), sup.stats()
        # Everything lives on the survivor now.
        hosts = sup.host_stats()
        assert sorted(hosts["h0"]["slots"]) == ["r0", "r1", "r2", "r3"]
        assert hosts["h1"]["slots"] == [] and hosts["h1"]["epoch"] == 1
        res = router.run(list(zip(PROMPTS, GENS)), results=True)
        for r, gold in zip(res, GOLDS):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == gold

        evts = [e.as_dict() for e in obs_events.default_ring().tail(0)[0]]
        downs = [e for e in evts if e["kind"] == "host_down"]
        assert len(downs) == 1, downs  # ONE event for the whole host
        assert downs[0]["fields"]["host"] == "h1"
        assert sorted(downs[0]["fields"]["slots"]) == ["r1", "r3"]
        fo = [e["fields"] for e in evts if e["kind"] == "spawn_failover"]
        assert sorted(f["slot"] for f in fo) == ["r1", "r3"]
        assert all(f == {"slot": f["slot"], "from_host": "h1",
                         "to_host": "h0"} for f in fo)
        snap = obs_metrics.default_registry().snapshot()
        hd = snap["tdt_supervisor_host_down_total"]["series"]
        assert {s["labels"]["host"]: s["value"] for s in hd} == {
            "h0": 0, "h1": 1,
        }
        up = snap["tdt_host_up"]["series"]
        assert {s["labels"]["host"]: s["value"] for s in up} == {
            "h0": 1.0, "h1": 0.0,
        }
    finally:
        sup.shutdown()


@needs_procs
def test_spawn_refused_host_drives_failover(fresh_telemetry):
    """A host that refuses the respawn (the ``launcher.spawn`` seam)
    costs one ``spawn`` failure and a FAILOVER: the slot re-places on
    the next up host and comes back healthy there — still under the
    backoff schedule, never a hot loop."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    laun = FakeHostLauncher(("h0", "h1"))
    sup = FleetSupervisor(
        _stub_specs(2, delay_s=0.0, hosts=["h0", "h1"]),
        launcher=laun, heartbeat_s=0.1, heartbeat_timeout_s=1.0,
        heartbeat_misses=2, respawn_backoff_s=0.2,
        spawn_timeout_s=120.0, crash_limit=4,
    )
    try:
        router = sup.start()
        with FaultPlan(seed=2).refuse_spawn(host="h0", times=9):
            os.kill(router.replica("r0").pid, signal.SIGKILL)
            assert sup.wait_for(
                lambda: sup.slot("r0").spec.host == "h1", timeout_s=30
            ), sup.stats()
            assert sup.wait_healthy(2, timeout_s=60), sup.stats()
        evts = [e.as_dict() for e in obs_events.default_ring().tail(0)[0]]
        fo = [e["fields"] for e in evts
              if e["kind"] == "spawn_failover"]
        assert {"slot": "r0", "from_host": "h0", "to_host": "h1"} in fo
        # An independent single-process crash is NOT a host_down.
        assert not sup.host_stats()["h0"]["down"]
        assert all(e["kind"] != "host_down" for e in evts)
        res = router.run([(PROMPTS[0], GENS[0])], results=True)
        assert res[0].tokens.tolist() == GOLDS[0]
    finally:
        sup.shutdown()


@needs_procs
@pytest.mark.slow
def test_hang_host_zombie_thaw_latches_zero(fresh_telemetry):
    """The epoch-fence acceptance: SIGSTOPping a whole host mid-batch
    classifies as ONE host_down; the requests re-route and finish
    bit-exact on the survivor; and when the zombie host THAWS, its
    late completions hit the fence — ``fenced_result_dropped`` fires
    and the dead generation latches ZERO results into the fleet."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    laun = FakeHostLauncher(("h0", "h1"))
    sup = FleetSupervisor(
        _stub_specs(3, delay_s=0.4, hosts=["h0", "h1", "h1"]),
        launcher=laun, heartbeat_s=0.1, heartbeat_timeout_s=1.0,
        heartbeat_misses=2, respawn_backoff_s=0.2,
        spawn_timeout_s=120.0,
        router_kw={"request_timeout_s": 1.5},
    )
    try:
        router = sup.start()
        zombies = [router.replica("r1"), router.replica("r2")]
        # Freeze the WHOLE h1 host the instant a batch lands on it:
        # the host.down seam fires mid-flight, exactly like a machine
        # wedging with requests on the wire.
        with FaultPlan(seed=4).hang_host(laun, host="h1") as plan:
            res = router.run(list(zip(PROMPTS, GENS)), results=True)
            assert plan.fired
            for r, gold in zip(res, GOLDS):
                assert r.status == "ok", (r.status, r.reason)
                assert r.tokens.tolist() == gold
            assert sup.wait_for(
                lambda: sup.host_stats()["h1"]["down"], timeout_s=30
            ), sup.stats()
            assert sup.wait_healthy(3, timeout_s=60), sup.stats()
            # Both h1 replicas are fenced under the down epoch.
            assert all(z.fenced for z in zombies)
            assert {z.fence_epoch for z in zombies} == {1}
            # Thaw: the zombie children resume and push completions
            # for tickets the fleet already finished elsewhere.
            laun.thaw_host("h1")
            assert sup.wait_for(
                lambda: any(
                    e.kind == "fenced_result_dropped"
                    for e in obs_events.default_ring().tail(0)[0]
                ),
                timeout_s=30,
            )
        # The fence held: the dead generation latched NOTHING.
        for z in zombies:
            assert z.served == 0 and z.runs == 0
        evts = [e.as_dict() for e in obs_events.default_ring().tail(0)[0]]
        downs = [e for e in evts if e["kind"] == "host_down"]
        assert len(downs) == 1 and downs[0]["fields"]["host"] == "h1"
        # Rejoin refused: the thawed host takes no placements until an
        # operator revives it.
        assert sup.host_stats()["h1"]["down"]
        assert sup._pick_host() == "h0"
    finally:
        sup.shutdown()


@needs_procs
@pytest.mark.slow
def test_add_slot_spreads_and_revive_reopens(fresh_telemetry):
    """Autoscaler-shaped growth over hosts: ``add_slot`` without a
    pinned host avoids concentrating the pool (the new slot lands on
    the emptier host), and after kill → revive the host takes NEW
    generations again while its fence epoch stays bumped."""
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    laun = FakeHostLauncher(("h0", "h1"))
    sup = FleetSupervisor(
        _stub_specs(2, delay_s=0.0, hosts=["h0", "h0"]),
        launcher=laun, heartbeat_s=0.1, heartbeat_timeout_s=1.0,
        heartbeat_misses=2, respawn_backoff_s=0.2,
        spawn_timeout_s=120.0,
    )
    try:
        sup.start()
        spec = stub_spec("g0", page_size=4, num_pages=64)
        sup.add_slot(spec)
        assert spec.host == "h1"  # the emptier host, not the crowd
        assert sup.wait_healthy(3, timeout_s=60)
        laun.kill_host("h1")
        assert sup.wait_for(
            lambda: sup.host_stats()["h1"]["down"], timeout_s=30
        )
        assert sup.wait_healthy(3, timeout_s=60)
        # Revive (the machine came back, fresh boot): placement reopens
        # under the SAME epoch — only new generations land there.
        laun.set_down("h1", False)
        sup.revive_host("h1")
        st = sup.host_stats()["h1"]
        assert not st["down"] and st["epoch"] == 1
        spec2 = stub_spec("g1", page_size=4, num_pages=64)
        sup.add_slot(spec2)
        assert spec2.host == "h1"
        assert sup.wait_healthy(4, timeout_s=60)
    finally:
        sup.shutdown()
