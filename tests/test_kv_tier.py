"""Durable KV tier tests (docs/serving.md "Tiered KV",
docs/scale-out.md "Durable snapshots").

Layers of evidence:

- pure :class:`PageStore` semantics — codec/integrity, RAM LRU within
  capacity, disk atomicity + reload, and the containment contract
  (corrupted/truncated/missing entries NEVER yield wrong bits) plus
  the seeded ``tier.put``/``tier.get`` fault seams — milliseconds, no
  model;
- engine-level spill/fault-back on the tiny model: eviction demotes
  full radix pages to the tier, a revisited prefix faults them back
  cheaper than re-prefill, outputs stay bit-exact vs tier-less
  goldens under bf16 AND int8 pools, corrupted entries degrade to
  re-prefill, and a randomized spill/fault-back stress keeps the
  pool/radix/tier audits clean (the conftest autouse auditor runs
  ``ContinuousEngine.audit`` — now tier-aware — after every test);
- crash durability: an engine whose run is killed mid-generation
  leaves checksummed snapshots on disk that a FRESH engine resumes
  bit-exactly, and (the PR 10 chaos suite's missing case) a stub
  process fleet whose supervisor AND children die is rebooted over
  the same ``resume_dir`` and finishes the re-submitted requests
  bit-exactly from the persisted snapshots.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from triton_distributed_tpu.models import kv_tier
from triton_distributed_tpu.models.kv_tier import (
    PREFIX_KIND,
    SNAP_KIND,
    PageStore,
    TierIntegrityError,
    chain_digest,
    request_digest,
)
from triton_distributed_tpu.runtime.faults import FaultPlan


def _can_spawn() -> bool:
    try:
        return subprocess.run(
            [sys.executable, "-c", "pass"], timeout=60
        ).returncode == 0
    except Exception:  # noqa: BLE001 — any failure means "cannot"
        return False


needs_procs = pytest.mark.skipif(
    not _can_spawn() or not hasattr(signal, "SIGKILL"),
    reason="child-process spawning unavailable on this platform",
)


# -- pure store: codec, LRU, disk, integrity, seams ------------------------


def test_digests_and_entry_codec():
    """Digests are stable, chain-exact, and collision-separated from
    request digests; the entry codec round-trips and every tamper
    class raises :class:`TierIntegrityError` instead of decoding."""
    assert chain_digest([1, 2, 3]) == chain_digest((1, 2, 3))
    assert chain_digest([1, 2, 3]) != chain_digest([1, 2, 4])
    assert request_digest([1, 2], 4) != request_digest([1, 2], 5)
    assert request_digest([1, 2], 4) == request_digest(
        np.asarray([1, 2], np.int32), 4
    )

    blob = kv_tier._encode("snap", "t1", {"a": [1, 2], "b": None})
    assert kv_tier._decode("snap", "t1", blob) == {"a": [1, 2], "b": None}
    with pytest.raises(TierIntegrityError, match="magic"):
        kv_tier._decode("snap", "t1", b"garbage")
    with pytest.raises(TierIntegrityError, match="truncated"):
        kv_tier._decode("snap", "t1", blob[:-2])
    with pytest.raises(TierIntegrityError, match="checksum"):
        flipped = bytearray(blob)
        flipped[-3] ^= 0xFF
        kv_tier._decode("snap", "t1", bytes(flipped))
    with pytest.raises(TierIntegrityError, match="expected"):
        kv_tier._decode("snap", "OTHER", blob)  # key mismatch
    with pytest.raises(TierIntegrityError, match="expected"):
        kv_tier._decode("prefix", "t1", blob)  # kind mismatch


def test_pagestore_lru_capacity_and_stats():
    """RAM-only store: hits/misses count, LRU eviction keeps bytes
    under capacity and evicts oldest-first, delete removes, audit is
    clean throughout."""
    s = PageStore(capacity_bytes=4096)
    assert s.get(SNAP_KIND, "absent") is None
    assert s.stats["misses"] == 1
    for i in range(4):
        assert s.put(SNAP_KIND, f"k{i}", {"pad": "x" * 256, "i": i})
    assert s.get(SNAP_KIND, "k0")["i"] == 0  # k0 is now most-recent
    assert s.stats["hits"] == 1
    # Push past capacity: k1 (the LRU) goes, k0 (touched) survives.
    big = {"pad": "y" * 3100}
    assert s.put(SNAP_KIND, "big", big)
    assert s.ram_bytes <= 4096
    assert s.stats["evictions"] >= 1
    assert s.get(SNAP_KIND, "k0")["i"] == 0
    assert s.get(SNAP_KIND, "k1") is None  # evicted (no disk tier)
    # An entry larger than the whole capacity is refused, not wedged.
    assert s.put(SNAP_KIND, "huge", {"pad": "z" * 8192}) is False
    assert s.stats["refused"] == 1
    s.delete(SNAP_KIND, "k0")
    assert s.get(SNAP_KIND, "k0") is None
    assert s.audit() == []
    snap = s.snapshot()
    assert snap["puts"] == 5 and snap["ram_bytes"] == s.ram_bytes


def test_pagestore_may_contain_guard(tmp_path):
    """``may_contain`` is the hot-path emptiness guard: False until the
    first successful put of that kind (per kind, monotone — deletes
    never reset it), seeded from disk at construction so a fresh
    process over a populated dir counts its predecessor's entries,
    and True for unknown kinds (conservative)."""
    s = PageStore(capacity_bytes=4096)
    assert not s.may_contain(PREFIX_KIND)
    assert not s.may_contain(SNAP_KIND)
    assert s.may_contain("unknown-kind")  # never under-probe
    # A refused put (oversized) leaves the store provably empty.
    assert s.put(SNAP_KIND, "huge", {"pad": "z" * 8192}) is False
    assert not s.may_contain(SNAP_KIND)
    assert s.put(SNAP_KIND, "t", {"a": 1})
    assert s.may_contain(SNAP_KIND)
    assert not s.may_contain(PREFIX_KIND)  # per-kind, not global
    s.delete(SNAP_KIND, "t")
    assert s.may_contain(SNAP_KIND)  # monotone: stays flipped
    # Disk prescan: a fresh store over a dir a prior process populated
    # reports non-empty without any put of its own.
    d = str(tmp_path / "tier")
    PageStore(capacity_bytes=4096, dir=d).put(
        PREFIX_KIND, chain_digest([1, 2]), {"chain": [1, 2]}
    )
    fresh = PageStore(capacity_bytes=4096, dir=d)
    assert fresh.may_contain(PREFIX_KIND)
    assert not fresh.may_contain(SNAP_KIND)


def test_pagestore_disk_persistence_and_atomicity(tmp_path):
    """Disk tier: entries survive into a FRESH store over the same dir
    (the restart path), RAM-evicted entries are still served from disk
    (and promoted), writes never leave a live ``.tmp``, and
    ``clear()`` empties both tiers."""
    d = str(tmp_path / "tier")
    s = PageStore(capacity_bytes=1 << 20, dir=d)
    for i in range(3):
        assert s.put(SNAP_KIND, f"t{i}", {"out": [i], "gen_len": 9,
                                          "prompt": [1, i]})
    assert s.put(PREFIX_KIND, chain_digest([5, 6]), {"chain": [5, 6]})
    # No tmp files linger after the atomic renames.
    leftovers = [
        f for root, _, files in os.walk(d) for f in files if ".tmp" in f
    ]
    assert leftovers == []
    # A fresh store sees every durable entry, by key.
    s2 = PageStore(capacity_bytes=1 << 20, dir=d)
    assert s2.keys(SNAP_KIND) == ["t0", "t1", "t2"]
    assert s2.get(SNAP_KIND, "t1")["out"] == [1]
    assert s2.stats["disk_hits"] == 1
    # RAM eviction demotes, not destroys: a tiny-RAM store still
    # serves from disk and promotes back into RAM.
    s3 = PageStore(capacity_bytes=600, dir=d)
    for i in range(8):
        s3.put(SNAP_KIND, f"fat{i}", {"pad": "x" * 300, "i": i})
    assert s3.stats["evictions"] >= 1
    assert s3.get(SNAP_KIND, "fat0")["i"] == 0  # from disk
    assert s3.stats["disk_hits"] >= 1
    # clear(): both tiers empty; prefix kind untouched by snap clear.
    removed = s3.clear(SNAP_KIND)
    assert removed > 0
    assert s3.keys(SNAP_KIND) == []
    assert PageStore(dir=d).keys(PREFIX_KIND) != []
    # fsync=False (the engine-owned scheduling-loop shape) still
    # round-trips through a fresh store: the atomic rename alone
    # carries process-crash durability.
    d2 = str(tmp_path / "nosync")
    s4 = PageStore(capacity_bytes=1 << 20, dir=d2, fsync=False)
    assert s4.put(SNAP_KIND, "ns", {"out": [7]})
    assert PageStore(dir=d2).get(SNAP_KIND, "ns")["out"] == [7]
    # Disk-bound prunes are PERMANENT deletions and count separately
    # from the (lossless) RAM LRU demotions.
    d3 = str(tmp_path / "bounded")
    s5 = PageStore(capacity_bytes=1 << 20, dir=d3,
                   disk_capacity_bytes=1200)
    for i in range(6):
        s5.put(SNAP_KIND, f"b{i}", {"pad": "y" * 300, "i": i})
    assert s5.stats["disk_evictions"] >= 1
    assert s5.stats["evictions"] == 0  # RAM had room: no demotions
    assert len(PageStore(dir=d3).keys(SNAP_KIND)) < 6  # gone from disk


def test_pagestore_integrity_containment(tmp_path):
    """The acceptance contract in miniature: corrupted bytes, a
    truncated file, a vanished file, and foreign garbage ALL read as
    None with the entry dropped and counted — wrong bits can never
    come out of ``get``."""
    from triton_distributed_tpu.obs import events as obs_events

    d = str(tmp_path / "tier")
    s = PageStore(capacity_bytes=1 << 20, dir=d)
    for name in ("corrupt", "truncate", "vanish", "garbage"):
        s.put(SNAP_KIND, name, {"payload": name * 8})

    path = PageStore(dir=d)._path(SNAP_KIND, "corrupt")
    raw = open(path, "rb").read()
    flipped = bytearray(raw)
    flipped[len(flipped) // 2] ^= 0xFF
    open(path, "wb").write(bytes(flipped))
    t_path = PageStore(dir=d)._path(SNAP_KIND, "truncate")
    open(t_path, "wb").write(open(t_path, "rb").read()[:-5])
    os.unlink(PageStore(dir=d)._path(SNAP_KIND, "vanish"))
    g_path = PageStore(dir=d)._path(SNAP_KIND, "garbage")
    open(g_path, "wb").write(b"not a tier entry at all")

    fresh = PageStore(capacity_bytes=1 << 20, dir=d)
    assert fresh.get(SNAP_KIND, "corrupt") is None
    assert fresh.get(SNAP_KIND, "truncate") is None
    assert fresh.get(SNAP_KIND, "vanish") is None
    assert fresh.get(SNAP_KIND, "garbage") is None
    assert fresh.stats["drops"] == 3  # vanish is a plain miss
    assert fresh.stats["misses"] == 1
    # Dropped entries are gone from disk too — the next lookup is a
    # clean miss, not a repeated integrity failure.
    assert fresh.get(SNAP_KIND, "corrupt") is None
    assert fresh.stats["misses"] == 2
    events, _ = obs_events.default_ring().tail(0, kind="tier_drop")
    assert len(events) >= 3
    # RAM-side corruption is detected the same way (entries are stored
    # as their checksummed wire bytes in BOTH tiers).
    r = PageStore(capacity_bytes=1 << 20)
    r.put(SNAP_KIND, "ram", {"x": 1})
    blob = bytearray(r._ram[(SNAP_KIND, "ram")])
    blob[len(blob) // 2] ^= 0xFF
    r._ram[(SNAP_KIND, "ram")] = bytes(blob)
    assert r.get(SNAP_KIND, "ram") is None
    assert r.stats["drops"] == 1


def test_tier_fault_seams():
    """The seeded ``tier.put``/``tier.get`` seams: refuse (put → False
    and the entry is NOT stored; get → transient miss, entry kept),
    corrupt (checksum drops the entry), slow (stalls, then proceeds) —
    and every firing is logged on the plan."""
    s = PageStore(capacity_bytes=1 << 20)
    with FaultPlan(seed=1).refuse_tier("put") as plan:
        assert s.put(SNAP_KIND, "a", {"x": 1}) is False
    assert plan.fired and s.stats["refused"] == 1
    assert s.get(SNAP_KIND, "a") is None

    s.put(SNAP_KIND, "b", {"x": 2})
    with FaultPlan(seed=1).refuse_tier("get") as plan:
        assert s.get(SNAP_KIND, "b") is None
    assert plan.fired and s.stats["errors"] == 1
    assert s.get(SNAP_KIND, "b") == {"x": 2}  # the entry survived

    with FaultPlan(seed=1).corrupt_tier("get") as plan:
        assert s.get(SNAP_KIND, "b") is None
    assert plan.fired and s.stats["drops"] == 1
    assert s.get(SNAP_KIND, "b") is None  # corrupt → dropped for good

    s.put(SNAP_KIND, "c", {"x": 3})
    with FaultPlan(seed=1).slow_tier(0.05, "get") as plan:
        t0 = time.monotonic()
        assert s.get(SNAP_KIND, "c") == {"x": 3}
        assert time.monotonic() - t0 >= 0.05
    assert plan.fired

    # Corruption injected at PUT time is caught at the next get.
    with FaultPlan(seed=1).corrupt_tier("put"):
        assert s.put(SNAP_KIND, "d", {"x": 4}) is True
    assert s.get(SNAP_KIND, "d") is None
    with pytest.raises(ValueError, match="op"):
        FaultPlan().refuse_tier("sideways")


# -- engine: spill, fault-back, containment, stress ------------------------


def _mk_reqs(rng, n_prefixes=2, prefix_tokens=32, tail=4, gen=3):
    reqs = []
    for _ in range(n_prefixes):
        pre = rng.integers(1, 200, size=prefix_tokens).astype(np.int32)
        t = rng.integers(1, 200, size=tail).astype(np.int32)
        reqs.append((np.concatenate([pre, t]), gen))
    return reqs


def test_engine_spill_and_fault_back_bitexact(ctx4):
    """Eviction under pool pressure spills full radix pages to the
    tier; re-admitting the evicted prefix faults them back (suffix-only
    prefill, counted) with outputs bit-identical to a tier-less
    engine. Runs the same proof on an int8 pool — codes + per-page
    scales travel as a pair."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    rng = np.random.default_rng(0)
    r1, r2 = _mk_reqs(rng)

    for kv_dtype in (None, "int8"):
        golds = [
            ContinuousEngine(
                model, max_batch=1, page_size=16, max_length=64,
                prefix_cache=True, kv_dtype=kv_dtype,
            ).run([r])[0]
            for r in (r1, r2)
        ]
        # 4-page pool: serving r2 must evict r1's chain — through the
        # tier instead of to nothing.
        eng = ContinuousEngine(
            model, max_batch=1, page_size=16, max_length=64,
            prefix_cache=True, num_pages=4, kv_dtype=kv_dtype,
            tier_bytes=32 << 20,
        )
        np.testing.assert_array_equal(eng.run([r1])[0], golds[0])
        np.testing.assert_array_equal(eng.run([r2])[0], golds[1])
        assert eng.last_stats["tier_spilled_pages"] >= 1
        np.testing.assert_array_equal(eng.run([r1])[0], golds[0])
        st = eng.last_stats
        assert st["tier_hits"] >= 1 and st["tier_faults"] >= 1
        assert st["tier_bytes"] > 0
        # Fault-back beat re-prefill: only the un-faulted suffix ran
        # through the prefill path.
        assert st["prefill_tokens"] < len(r1[0])
        assert st["prefix_hit_tokens"] >= 16
        assert st["tier"]["hits"] >= 1
        assert eng.audit() == []


def test_engine_tier_weight_identity(ctx4):
    """Durable entries are valid under the weights that produced them,
    never across a checkpoint swap: a prefix entry whose model
    fingerprint differs is refused at fault-back (dropped; admission
    re-prefills bit-exactly), and a snapshot carrying a foreign
    fingerprint degrades to a bit-exact replay instead of importing
    old-weight KV."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import (
        ContinuousEngine,
        Request,
    )

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    rng = np.random.default_rng(3)
    r1, r2 = _mk_reqs(rng)
    gold = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True,
    ).run([r1])[0]

    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, num_pages=4, tier_bytes=32 << 20,
    )
    np.testing.assert_array_equal(eng.run([r1])[0], gold)
    eng.run([r2])  # evict r1's chain through the tier
    assert eng.last_stats["tier_spilled_pages"] >= 1
    # Rewrite every prefix entry as if another checkpoint produced it.
    for key in eng.tier.keys(PREFIX_KIND):
        payload = eng.tier.get(PREFIX_KIND, key)
        payload["model_fp"] = "other-weights"
        assert eng.tier.put(PREFIX_KIND, key, payload)
    np.testing.assert_array_equal(eng.run([r1])[0], gold)  # re-prefilled
    assert eng.last_stats["tier_faults"] == 0
    assert eng.audit() == []

    # Snapshot side: crash a shared-store engine mid-generation, then
    # import its stamped leftover into same-weights engines — clean
    # fingerprint resumes, foreign fingerprint replays; both bit-exact.
    prompt = np.arange(1, 20, dtype=np.int32)
    gold2 = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True,
    ).run([(prompt, 6)])[0]
    shared = PageStore(capacity_bytes=1 << 20)
    crasher = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, snapshot_every=1, tier=shared,
    )
    with FaultPlan(seed=5).on("engine.decode", at=3,
                              exc=KeyboardInterrupt()):
        with pytest.raises(KeyboardInterrupt):
            crasher.run(
                [Request(prompt, 6, ticket_id="tkt-w")], results=True
            )
    assert crasher.audit() == []
    snap = shared.get(SNAP_KIND, "tkt-w")
    assert snap is not None and snap.get("model_fp")

    ok = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, tier_bytes=1 << 20,
    )
    out = ok.run([Request(prompt, 6, snapshot=dict(snap))], results=True)
    np.testing.assert_array_equal(out[0].tokens, gold2)
    assert ok.last_stats["migrated_in"] == 1

    bad = dict(snap)
    bad["model_fp"] = "other-weights"
    ok2 = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, tier_bytes=1 << 20,
    )
    out2 = ok2.run([Request(prompt, 6, snapshot=bad)], results=True)
    assert out2[0].status == "ok"
    np.testing.assert_array_equal(out2[0].tokens, gold2)
    assert ok2.last_stats["migration_fallbacks"] >= 1
    assert ok2.last_stats["migrated_in"] == 0


def test_engine_shared_tier_mismatch_skips_not_deletes(ctx4):
    """A mismatched probe against a SHARED store (``tier=``) degrades
    locally but never destroys the other engine's valid entry: an int8
    engine walking a bf16 engine's spilled chain re-prefills (zero
    faults), the entries survive, and the bf16 engine still faults
    them back afterwards. (Owned stores DO delete on mismatch —
    covered by the weight-identity test.)"""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    rng = np.random.default_rng(7)
    r1, r2 = _mk_reqs(rng)
    mk = dict(max_batch=1, page_size=16, max_length=64,
              prefix_cache=True)
    gold1, gold2 = (
        ContinuousEngine(model, **mk).run([r])[0] for r in (r1, r2)
    )
    gold1_i8 = ContinuousEngine(
        model, kv_dtype="int8", **mk
    ).run([r1])[0]

    shared = PageStore(capacity_bytes=32 << 20)
    a = ContinuousEngine(model, num_pages=4, tier=shared, **mk)
    np.testing.assert_array_equal(a.run([r1])[0], gold1)
    np.testing.assert_array_equal(a.run([r2])[0], gold2)  # spills r1
    assert a.last_stats["tier_spilled_pages"] >= 1
    keys_before = set(shared.keys(PREFIX_KIND))
    assert keys_before

    b = ContinuousEngine(model, kv_dtype="int8", tier=shared, **mk)
    np.testing.assert_array_equal(b.run([r1])[0], gold1_i8)
    assert b.last_stats["tier_hits"] == 0
    assert b.last_stats["tier_faults"] == 0
    assert set(shared.keys(PREFIX_KIND)) == keys_before  # intact

    np.testing.assert_array_equal(a.run([r1])[0], gold1)
    assert a.last_stats["tier_hits"] >= 1  # A still faults back
    assert a.audit() == [] and b.audit() == []


def test_engine_tier_events_and_metrics(ctx4, fresh_telemetry):
    """The tier ledger is mirrored into the registry and the event
    ring: spills, fault-backs, and the tdt_tier_* series line up with
    ``last_stats``."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.obs import metrics as obs_metrics

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    rng = np.random.default_rng(1)
    r1, r2 = _mk_reqs(rng)
    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, num_pages=4, tier_bytes=32 << 20,
    )
    eng.run([r1])
    eng.run([r2])
    eng.run([r1])
    kinds = [e.kind for e in obs_events.default_ring().tail(0)[0]]
    assert "tier_spill" in kinds and "tier_fault" in kinds
    snap = obs_metrics.default_registry().snapshot()
    spilled = snap["tdt_tier_spilled_pages_total"]["series"][0]["value"]
    faulted = snap["tdt_tier_faulted_pages_total"]["series"][0]["value"]
    assert spilled >= 1 and faulted >= 1
    # ISSUE-12 satellite: the deployed tier knobs ride
    # server_stats.engine next to kv_dtype.
    from triton_distributed_tpu.serving import ModelServer

    srv = ModelServer(eng)
    try:
        est = srv.server_stats["engine"]
        assert est["tier_bytes"] == 32 << 20
        assert est["tier_dir"] is None
        assert "kv_dtype" in est
    finally:
        srv._sock.close()


def test_engine_corrupt_tier_degrades_to_prefill(ctx4):
    """Failure containment: every tier entry corrupted in place still
    yields BIT-EXACT outputs — the checksum drops each entry and the
    admission re-prefills (tier_faults stays 0, drops count up)."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    rng = np.random.default_rng(2)
    r1, r2 = _mk_reqs(rng)
    gold = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True,
    ).run([r1])[0]
    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, num_pages=4, tier_bytes=32 << 20,
    )
    eng.run([r1])
    eng.run([r2])  # evicts + spills r1's chain
    assert eng.tier.snapshot()["ram_entries"] >= 1
    # Corrupt EVERY stored entry in place (RAM tier, no disk here).
    with eng.tier._lock:
        for k, blob in list(eng.tier._ram.items()):
            b = bytearray(blob)
            b[len(b) // 2] ^= 0xFF
            eng.tier._ram[k] = bytes(b)
    np.testing.assert_array_equal(eng.run([r1])[0], gold)
    st = eng.last_stats
    assert st["tier_faults"] == 0
    assert st["tier"]["drops"] >= 1
    assert st["prefill_tokens"] >= len(r1[0]) - 16  # re-prefilled
    assert eng.audit() == []


def test_engine_tier_fault_seams_degrade(ctx4):
    """Injected tier faults at the engine level: a refused spill
    behaves like the pre-tier drop, a refused fault-back read like a
    miss — outputs bit-exact either way."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    rng = np.random.default_rng(3)
    r1, r2 = _mk_reqs(rng)
    gold = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True,
    ).run([r1])[0]
    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, num_pages=4, tier_bytes=32 << 20,
    )
    eng.run([r1])
    with FaultPlan(seed=4).refuse_tier("put", times=99) as plan:
        eng.run([r2])  # every spill refused
    assert plan.fired
    assert eng.last_stats["tier_spilled_pages"] == 0
    np.testing.assert_array_equal(eng.run([r1])[0], gold)  # re-prefill
    # Now let spills through, then refuse the reads.
    eng.run([r2])
    assert eng.last_stats["tier_spilled_pages"] >= 1
    with FaultPlan(seed=4).refuse_tier("get", times=99) as plan:
        np.testing.assert_array_equal(eng.run([r1])[0], gold)
    assert plan.fired
    assert eng.last_stats["tier_faults"] == 0
    assert eng.audit() == []


def test_engine_randomized_spill_faultback_stress(ctx4):
    """Randomized shared-prefix traffic over a pool far smaller than
    the population, tier on: every output equals its tier-less golden,
    and the pool partition (free ∪ slots ∪ tree) plus the tier audits
    stay clean after every round (the autouse fixture re-audits at
    teardown)."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    rng = np.random.default_rng(5)
    bases = [
        rng.integers(1, 200, size=32).astype(np.int32) for _ in range(3)
    ]
    golden_engine = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64,
        prefix_cache=True,
    )
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64,
        prefix_cache=True, num_pages=6, tier_bytes=32 << 20,
    )
    golds: dict = {}
    for _ in range(8):
        base = bases[int(rng.integers(len(bases)))]
        cut = int(rng.integers(16, len(base) + 1))
        tail = rng.integers(1, 200, size=int(rng.integers(1, 4)))
        prompt = np.concatenate([base[:cut], tail]).astype(np.int32)
        gen = int(rng.integers(1, 4))
        key = (tuple(int(t) for t in prompt), gen)
        if key not in golds:
            golds[key] = golden_engine.run([(prompt, gen)])[0]
        out = eng.run([(prompt, gen)])[0]
        np.testing.assert_array_equal(out, golds[key])
        assert eng.audit() == []
        owned = list(eng.pool.free) + [
            n.page for n in eng.prefix.walk()
        ]
        assert len(owned) == len(set(owned))
    assert eng.last_stats["tier"]["puts"] >= 1  # the tier actually ran


def test_audit_catches_tier_chain_drift(ctx4):
    """The tier-residency audit cross-check: an entry whose payload
    chain no longer matches its digest key (or a tree node's chain) is
    reported — the drift that would fault wrong KV back under a prompt
    if it went unseen."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    rng = np.random.default_rng(6)
    r1, _ = _mk_reqs(rng)
    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, tier_bytes=32 << 20,
    )
    eng.run([r1])
    # Fabricate a drifted entry: correct checksum, wrong chain for the
    # digest key it is stored under.
    chain = [int(t) for t in r1[0][:16]]
    key = chain_digest(chain)
    bad = kv_tier.prefix_payload(
        [9] * 16, 16, None,
        np.zeros((2, 4, 16, 32), np.float32),
        np.zeros((2, 4, 16, 32), np.float32),
    )
    blob = kv_tier._encode(PREFIX_KIND, key, bad)
    with eng.tier._lock:
        eng.tier._ram[(PREFIX_KIND, key)] = blob
        eng.tier._ram_bytes += len(blob)
    problems = eng.audit()
    assert any("digest key" in p or "different token chain" in p
               for p in problems), problems
    eng.tier.delete(PREFIX_KIND, key)  # leave the engine clean
    assert eng.audit() == []


# -- crash durability: engine snapshots on disk ----------------------------


def test_engine_snapshot_buffer_survives_crash(ctx4, tmp_path):
    """``snapshot_every`` + a disk tier: a run killed mid-generation
    leaves checksummed snapshots on disk; a FRESH engine (new process
    stand-in) imports the leftover and finishes BIT-EXACTLY vs an
    uninterrupted golden — the engine-side half of supervisor-restart
    recovery."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import (
        ContinuousEngine,
        Request,
    )

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    prompt = np.arange(1, 20, dtype=np.int32)
    gold = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True,
    ).run([(prompt, 8)])[0]

    d = str(tmp_path / "tier")
    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, snapshot_every=1, tier_dir=d,
    )
    # The crash must END the loop (a structured in-process failure
    # would keep running and prune its own buffer — correct, but not
    # a crash): KeyboardInterrupt escapes the decode step guard's
    # Exception boundary exactly like a process-killing signal, and
    # the durable entries written at earlier round boundaries stay.
    with FaultPlan(seed=7).on(
        "engine.decode", at=5, exc=KeyboardInterrupt()
    ):
        with pytest.raises(KeyboardInterrupt):
            eng.run(
                [Request(prompt, 8, ticket_id="tkt-1")], results=True
            )
    assert eng.audit() == []  # the abort teardown left the pool clean

    # A fresh store over the same dir (what a restarted process sees)
    # holds the last pre-crash snapshot, integrity-checked.
    store = PageStore(dir=d)
    assert store.keys(SNAP_KIND) == ["tkt-1"]
    snap = store.get(SNAP_KIND, "tkt-1")
    assert snap is not None and len(snap["out"]) >= 1

    fresh = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True,
    )
    out = fresh.run([Request(prompt, 8, snapshot=snap)], results=True)
    assert out[0].status == "ok"
    np.testing.assert_array_equal(out[0].tokens, gold)
    st = fresh.last_stats
    assert st["migrated_in"] == 1 and st["migrated_in_tokens"] >= 1

    # A RESPAWNED process over the same dir (fresh object: empty
    # _tier_snap_keys) clears its crashed predecessor's leftovers at
    # its first run() start — entries mean "crash", never "history";
    # without the owned-store clear they'd accumulate per crash cycle.
    respawn = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, snapshot_every=1, tier_dir=d,
    )
    respawn.run([Request(prompt, 2, ticket_id="tkt-2")], results=True)
    assert "tkt-1" not in PageStore(dir=d).keys(SNAP_KIND)
    respawn.run([(prompt, 1)])
    assert PageStore(dir=d).keys(SNAP_KIND) == []

    # A SHARED store (tier= passed in) is NOT ours to sweep: run()
    # start deletes only this engine's own keys, never a sibling
    # replica's live snapshots.
    shared = PageStore(capacity_bytes=1 << 20)
    shared.put(SNAP_KIND, "sibling-tkt", {"out": [1]})
    ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64,
        prefix_cache=True, tier=shared,
    ).run([(prompt, 1)])
    assert shared.get(SNAP_KIND, "sibling-tkt") is not None


# -- supervisor: pull visibility + restart resume --------------------------


def test_supervisor_pull_failure_visible(fresh_telemetry):
    """ISSUE-12 satellite: a failed snapshot pull is COUNTED and
    evented (it used to vanish into a bare ``continue``) — a
    permanently wedged exporter shows as a monotone
    tdt_supervisor_snapshot_pull_failures_total ramp."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        ReplicaSpec,
    )

    sup = FleetSupervisor(
        [ReplicaSpec("r0", ["true"])], snapshot_s=0.01,
    )

    class _Wedged:
        name = "r0#0"
        state = "healthy"

        def export_slots(self, timeout=None):
            raise ConnectionResetError("exporter wedged")

    sup._slots[0].replica = _Wedged()
    sup._pull_snapshots()
    sup._pull_snapshots()
    snap = obs_metrics.default_registry().snapshot()
    series = snap["tdt_supervisor_snapshot_pull_failures_total"]["series"]
    assert [s["value"] for s in series
            if s["labels"]["replica"] == "r0"] == [2]
    events, _ = obs_events.default_ring().tail(
        0, kind="snapshot_pull_failed"
    )
    assert len(events) == 2
    assert "exporter wedged" in events[-1].fields["reason"]

    # A non-dict answer counts too (a half-broken exporter).
    class _Wrong(_Wedged):
        def export_slots(self, timeout=None):
            return ["not", "a", "dict"]

    sup._slots[0].replica = _Wrong()
    sup._pull_snapshots()
    snap = obs_metrics.default_registry().snapshot()
    series = snap["tdt_supervisor_snapshot_pull_failures_total"]["series"]
    assert [s["value"] for s in series
            if s["labels"]["replica"] == "r0"] == [3]


@needs_procs
def test_supervisor_restart_resume_bitexact(tmp_path, fresh_telemetry):
    """ISSUE-12 acceptance (the PR 10 chaos suite's missing case): a
    stub fleet with snapshot pulls persisted under ``resume_dir`` is
    killed mid-batch — children SIGKILLed, supervisor abandoned
    (never drained, so the store keeps its leftovers). A NEW
    supervisor boots over the same dir, the requests are re-submitted
    (fresh ticket ids), and every one finishes BIT-EXACT against the
    stub's pure generator with tokens restored from the persisted
    snapshots rather than regenerated."""
    from triton_distributed_tpu.models.stub import stub_generate
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    resume = str(tmp_path / "resume")
    prompts = [np.arange(1, 9, dtype=np.int32),
               np.arange(20, 30, dtype=np.int32)]
    gens = [8, 8]
    golds = [stub_generate(p, g) for p, g in zip(prompts, gens)]

    def mk_sup():
        return FleetSupervisor(
            [stub_spec("r0", delay_s=2.5, page_size=4, num_pages=64)],
            heartbeat_s=0.05, snapshot_s=0.05, resume_dir=resume,
            spawn_timeout_s=120.0,
        )

    sup = mk_sup()
    router = sup.start()
    results: dict = {}

    def drive():
        results["res"] = router.run(
            list(zip(prompts, gens)), results=True
        )

    th = threading.Thread(target=drive, daemon=True)
    th.start()
    # Wait until the durable store holds real MID-generation progress
    # (some request with 0 < out < gen_len persisted), then "crash"
    # everything: SIGKILL the child, abandon the supervisor WITHOUT
    # drain (a drain would clear the store — leftovers mean crash).
    store = PageStore(dir=resume)

    def progressed():
        for k in store.keys(SNAP_KIND):
            snap = store.peek(SNAP_KIND, k) or {}
            out = snap.get("out") or []
            if 0 < len(out) < int(snap.get("gen_len", 0)):
                return True
        return False

    assert sup.wait_for(progressed, timeout_s=60), store.keys(SNAP_KIND)
    sup._stop.set()  # the monitor must not respawn into the "crash"
    if sup._thread is not None:
        sup._thread.join(timeout=10)
    proc = router.replicas[0].proc
    os.kill(router.replicas[0].pid, signal.SIGKILL)
    proc.wait(timeout=10)
    th.join(timeout=60)
    assert not th.is_alive()
    # The in-flight work failed (no survivor to re-route to) — its
    # progress now lives ONLY in the durable store.
    assert any(r.status != "ok" for r in results["res"])
    assert len(PageStore(dir=resume).keys(SNAP_KIND)) >= 1

    # Reboot over the same dir; re-submit the same requests (new
    # ticket ids — the digest match is what finds the leftovers).
    sup2 = mk_sup()
    try:
        router2 = sup2.start()
        res2 = router2.run(list(zip(prompts, gens)), results=True)
        for r, gold in zip(res2, golds):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == gold
        # Tokens were RESTORED, not regenerated: the fleet's cumulative
        # migrated_in ledger proves the snapshots were consumed.
        st = router2.last_stats
        assert st["migrated_in_tokens"] >= 1
        events, _ = obs_events.default_ring().tail(
            0, kind="snapshot_resume"
        )
        assert any(e.fields.get("restart") for e in events)
        # Consumed leftovers are deleted — a third submission of the
        # same prompts decodes fresh (still bit-exact, of course).
        res3 = router2.run(list(zip(prompts, gens)), results=True)
        for r, gold in zip(res3, golds):
            assert r.tokens.tolist() == gold
    finally:
        sup2.shutdown()
    # The CLEAN shutdown cleared the resume store.
    assert PageStore(dir=resume).keys(SNAP_KIND) == []
