"""Long-context serving coverage (ISSUE-20).

What's covered, and why tier-1:

- context-parallel chunked prefill (``cp=``): bit-exact with the cp=1
  reference on the tiny model, the split-phase KV-exchange tracer
  records a gap-free ring (``validate_cp_ring``), and the overlap
  report carries the measured hidden fraction — a scheduling
  regression (exchange serialized after attention, or a dropped
  block) has to FAIL tier-1, not wait for a long_context_bench run.
- sharded-slot paged decode (``rank_page_budget=``): a slot whose KV
  exceeds the per-rank budget demotes cold pages to the KV tier and
  decodes through the lse_combine partial merge — greedy tokens stay
  bit-exact with a big-pool reference, tier faults are observed, and
  the pool/radix/tier audit stays clean (the conftest autouse fixture
  re-audits after every test).
- sharded snapshot → wire → import (the gather-stitch codec): a
  migrated sharded slot resumes on a PLAIN engine bit-exact with the
  uninterrupted run — the ROADMAP item 1 sharded-migration seam.
- ctor validation: ``max_length % page_size`` at BOTH engines, and
  the cp/rank_page_budget knob guards, each naming its values.
- interpret-mode parity for the kernels the tentpole builds on:
  ``ring_attention`` and ``distributed_flash_decode_2level`` vs dense
  references in bf16, and the 2-level decode over int8 shards with
  per-chunk scales (the ISSUE-20 satellite closing the "serving
  depends on unexercised kernels" gap).
- the ``document`` loadgen class: same-seed-identical, rng-stream
  compatible with mix-less specs (the cross-PR trace-identity
  contract), JSONL round-trip, and the ``--classes`` wire format.
- CLI refusals: ``--cp``/``--rank-page-budget`` fail fast BY FLAG
  NAME on incompatible paths (stub engine, mega mode, no tier)
  before any model loads.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.runtime import mesh as mesh_mod


@pytest.fixture(scope="module")
def lc_model():
    """ONE tiny model on a tp=4 mesh for the whole module (the
    test_migration.py rationale: jit caches live on the model, so
    every engine in the file shares one compile). tp=4 exercises the
    sharded decode/prefill programs' real in_specs."""
    ctx = mesh_mod.initialize_distributed(
        tp=4, devices=jax.devices()[:4]
    )
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    yield model
    mesh_mod.finalize_distributed()


def make_engine(model, **kw):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    kw.setdefault("max_batch", 1)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_length", 256)
    return ContinuousEngine(model, **kw)


PROMPT_CP = np.random.default_rng(7).integers(
    1, 200, size=100
).astype(np.int32)
PROMPT_LONG = np.random.default_rng(8).integers(
    1, 200, size=120
).astype(np.int32)


# ---------------------------------------------------------------------------
# ctor validation


def test_max_length_page_size_validation(lc_model):
    """A misaligned (max_length, page_size) pair must refuse at
    construction NAMING BOTH VALUES — before it, ``pps`` silently
    truncated and the tail tokens had no page."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.models.engine import Engine

    with pytest.raises(ValueError, match=r"100.*not a multiple.*16"):
        ContinuousEngine(
            lc_model, max_batch=1, page_size=16, max_length=100
        )
    # Engine validates against the model's cfg.max_length (128 for
    # tiny) — 48 does not divide it.
    with pytest.raises(ValueError, match=r"max_length=128.*page_size=48"):
        Engine(lc_model, paged=True, page_size=48)
    with pytest.raises(ValueError, match=r"max_length.*page_size"):
        Engine(lc_model, paged=True, page_size=16).serve(
            [np.arange(1, 9, dtype=np.int32)], gen_len=1, max_length=100
        )


def test_longctx_knob_validation(lc_model):
    """cp/rank_page_budget guard rails, each refusing with the value
    it saw (docs/serving.md "Long-context serving")."""
    with pytest.raises(ValueError, match="cp must be >= 1"):
        make_engine(lc_model, cp=0)
    with pytest.raises(ValueError, match="prefix_cache"):
        make_engine(lc_model, cp=2)
    with pytest.raises(ValueError, match="chunked xla/pallas"):
        make_engine(lc_model, cp=2, prefix_cache=True, speculative=2)
    with pytest.raises(ValueError, match="not a multiple"):
        make_engine(lc_model, rank_page_budget=40, tier_bytes=1 << 20)
    with pytest.raises(ValueError, match=">= 2 pages"):
        make_engine(lc_model, rank_page_budget=16, tier_bytes=1 << 20)
    with pytest.raises(ValueError, match="requires a KV tier"):
        make_engine(lc_model, rank_page_budget=64)
    with pytest.raises(ValueError, match="xla/pallas decode"):
        make_engine(
            lc_model, rank_page_budget=64, tier_bytes=1 << 20,
            speculative=2,
        )


# ---------------------------------------------------------------------------
# context-parallel prefill


def test_cp_prefill_bit_exact(lc_model):
    """cp=2 prefill == cp=1 reference token-for-token; the exchange
    tracer shows a gap-free ring and a well-formed overlap report."""
    from triton_distributed_tpu.models import long_context as lc

    gold = make_engine(lc_model, prefix_cache=True).run(
        [(PROMPT_CP, 4)]
    )[0]
    eng = make_engine(lc_model, prefix_cache=True, cp=2)
    got = eng.run([(PROMPT_CP, 4)])[0]
    np.testing.assert_array_equal(got, gold)

    rep = lc.cp_overlap_report(eng.cp_tracer)
    assert rep["blocks"] > 0 and rep["exchanges"] > 0
    assert rep["exchange_bytes"] > 0
    assert 0.0 <= rep["hidden_fraction"] <= 1.0
    assert lc.validate_cp_ring(eng.cp_tracer, rep["blocks"], 2) == []
    assert eng.last_stats["cp_prefills"] == 1
    assert eng.last_stats["cp_blocks"] == rep["blocks"]
    assert eng.last_stats["cp_exchange_bytes"] == rep["exchange_bytes"]
    assert eng.audit() == []


def test_cp_metrics_pretouched(lc_model):
    """Every tdt_cp_*/tdt_longctx_* counter exists at 0 on a COLD
    engine (the PR 15/18 pre-touch pattern): a fleet scrape sees the
    full catalog before the first long request arrives."""
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import metrics as obs_metrics

    prev = obs.is_enabled()
    obs.set_enabled(True)
    obs_metrics.default_registry().clear()
    try:
        make_engine(lc_model, prefix_cache=True)
        names = set(obs_metrics.default_registry().snapshot())
        for stem in (
            "cp_prefills", "cp_blocks", "cp_exchange_bytes",
            "cp_exchange_us", "cp_hidden_us",
            "longctx_sharded_slots", "longctx_demoted_pages",
            "longctx_tier_faults", "longctx_tier_bytes",
            "longctx_decode_steps",
        ):
            assert f"tdt_{stem}_total" in names, stem
    finally:
        obs_metrics.default_registry().clear()
        obs.set_enabled(prev)


# ---------------------------------------------------------------------------
# sharded-slot decode + tier-backed paging


def test_sharded_slot_decode_parity(lc_model):
    """A slot whose KV exceeds rank_page_budget demotes cold pages to
    the tier, faults them back per decode step, and still matches the
    big-pool reference token-for-token with a clean audit."""
    gold = make_engine(lc_model).run([(PROMPT_LONG, 6)])[0]
    eng = make_engine(
        lc_model, rank_page_budget=64, tier_bytes=32 << 20, num_pages=6,
    )
    got = eng.run([(PROMPT_LONG, 6)])[0]
    np.testing.assert_array_equal(got, gold)
    assert eng.last_stats["longctx_sharded_slots"] == 1
    assert eng.last_stats["longctx_demoted_pages"] > 0
    assert eng.last_stats["longctx_tier_faults"] > 0
    assert eng.last_stats["longctx_decode_steps"] >= 5
    assert eng.audit() == []


def test_sharded_snapshot_roundtrip(lc_model):
    """Sharded slot → handoff → import into a PLAIN engine resumes
    bit-exact (the gather-stitch codec re-materializes cold pages from
    the tier into one absolute-order snapshot)."""
    from triton_distributed_tpu.models.continuous import Request

    gold = make_engine(lc_model).run([(PROMPT_LONG, 6)])[0]
    A = make_engine(
        lc_model, rank_page_budget=64, tier_bytes=32 << 20, num_pages=6,
    )
    A.request_handoff(after_rounds=3)
    r = A.run([(PROMPT_LONG, 6)], results=True)[0]
    assert r.status == "migrated" and r.snapshot is not None
    assert A.audit() == []
    B = make_engine(lc_model)
    out = B.run(
        [Request(PROMPT_LONG, 6, snapshot=r.snapshot)], results=True
    )[0]
    np.testing.assert_array_equal(out.tokens, gold)
    assert B.last_stats["migrated_in"] == 1
    assert B.audit() == [] and A.audit() == []


# ---------------------------------------------------------------------------
# kernel parity (the ops the tentpole builds on), bf16 + int8


def test_ring_attention_bf16(ctx4, rng):
    """Causal ring attention in bf16 vs the dense causal reference —
    the cp-prefill kernel substrate at serving's own dtype."""
    from triton_distributed_tpu.ops.attention import (
        mha_reference,
        ring_attention,
    )

    s, hq, hkv, hd = 256, 4, 2, 64
    q = jnp.asarray(rng.standard_normal((hq, s, hd)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((hkv, s, hd)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((hkv, s, hd)), jnp.bfloat16)
    f = ctx4.shard_map(
        functools.partial(
            ring_attention, axis="tp", causal=True, block_q=64,
            block_k=64,
        ),
        in_specs=(P(None, "tp", None),) * 3,
        out_specs=P(None, "tp", None),
    )
    out = f(q, k, v)
    assert out.dtype == jnp.bfloat16
    ref = mha_reference(
        q[None].astype(jnp.float32), k[None].astype(jnp.float32),
        v[None].astype(jnp.float32), causal=True,
    )[0]
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=5e-2,
        rtol=5e-2,
    )


def test_distributed_flash_decode_2level_bf16(ctx2x4, rng):
    """Two-level (DCN×ICI) decode merge in bf16 vs the dense golden —
    the sharded-slot decode substrate at serving's own dtype."""
    from triton_distributed_tpu.ops.attention import (
        distributed_flash_decode_2level,
        gqa_decode_reference,
    )

    b, hq, hkv, s, hd = 2, 4, 2, 256, 64
    q = jnp.asarray(rng.standard_normal((b, hq, hd)), jnp.bfloat16)
    kc = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.bfloat16)
    vc = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.bfloat16)
    lens = jnp.asarray([200, 37], jnp.int32)
    f = ctx2x4.shard_map(
        functools.partial(
            distributed_flash_decode_2level, inner_axis="tp",
            outer_axis="dp", chunk_k=32, method="xla", ctx=ctx2x4,
        ),
        in_specs=(P(), P(None, None, ("dp", "tp"), None),
                  P(None, None, ("dp", "tp"), None), P()),
        out_specs=P(),
    )
    out = f(q, kc, vc, lens)
    assert out.dtype == jnp.bfloat16
    ref = gqa_decode_reference(
        q.astype(jnp.float32), kc.astype(jnp.float32),
        vc.astype(jnp.float32), lens,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=5e-2,
        rtol=5e-2,
    )


def test_distributed_flash_decode_2level_int8(ctx2x4, rng):
    """Two-level decode over int8 shards with per-chunk scales: each
    rank dequantizes in-kernel, the (O, LSE) combine is unchanged —
    the layout a quantized sharded slot streams through."""
    from triton_distributed_tpu.models.paged_kv_cache import quantize_pages
    from triton_distributed_tpu.ops.attention import (
        distributed_flash_decode_2level,
        gqa_decode_reference,
    )

    b, hq, hkv, s, hd, chunk = 2, 4, 2, 256, 64, 32
    q = jnp.asarray(rng.standard_normal((b, hq, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.float32)
    lens = jnp.asarray([180, 47], jnp.int32)
    k_q, k_sc = quantize_pages(k.reshape(b, hkv, s // chunk, chunk, hd))
    v_q, v_sc = quantize_pages(v.reshape(b, hkv, s // chunk, chunk, hd))
    def shard_fn(q, k, v, lens, ks, vs):
        return distributed_flash_decode_2level(
            q, k, v, lens, inner_axis="tp", outer_axis="dp",
            chunk_k=chunk, method="xla", k_scale=ks, v_scale=vs,
            ctx=ctx2x4,
        )

    f = ctx2x4.shard_map(
        shard_fn,
        in_specs=(P(), P(None, None, ("dp", "tp"), None),
                  P(None, None, ("dp", "tp"), None), P(),
                  P(None, None, ("dp", "tp")),
                  P(None, None, ("dp", "tp"))),
        out_specs=P(),
    )
    out = f(
        q, k_q.reshape(b, hkv, s, hd), v_q.reshape(b, hkv, s, hd),
        lens, k_sc, v_sc,
    )
    ref = gqa_decode_reference(q, k, v, lens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=0.1, rtol=0.1
    )


# ---------------------------------------------------------------------------
# document loadgen class


def _doc_spec(**kw):
    import perf.loadgen as lg

    kw.setdefault("n_requests", 12)
    kw.setdefault("seed", 3)
    kw.setdefault("doc_min", 64)
    kw.setdefault("doc_max", 96)
    return lg.LoadSpec(**kw)


def test_document_class_draws():
    """The document class lands 10k-scale bodies (shrunk here) on its
    rows only, deterministically per seed."""
    import perf.loadgen as lg

    spec = _doc_spec(
        class_mix=(("interactive", 2.0), ("document", 1.0))
    )
    a = lg.generate_trace(spec)
    b = lg.generate_trace(spec)
    assert a == b  # same-seed-identical
    docs = [r for r in a if r["slo_class"] == "document"]
    rest = [r for r in a if r["slo_class"] != "document"]
    assert docs and rest
    for r in docs:
        assert len(r["prompt"]) >= spec.prefix_len + spec.doc_min
    for r in rest:
        assert len(r["prompt"]) <= spec.prefix_len + spec.suffix_max


def test_document_class_stream_compatible():
    """The rng-stream contract: document draws land strictly AFTER all
    pre-existing draws, so a mix WITHOUT the class consumes the stream
    exactly as before — and the doc knobs are inert on such specs."""
    import perf.loadgen as lg

    base = _doc_spec(class_mix=(("interactive", 1.0),))
    tweaked = _doc_spec(
        class_mix=(("interactive", 1.0),), doc_min=100, doc_max=200
    )
    assert lg.generate_trace(base) == lg.generate_trace(tweaked)
    # Adding the document class changes only class labels and the
    # relabeled rows' prompts — arrivals and gen_lens are upstream
    # draws and stay identical.
    mixed = lg.generate_trace(
        _doc_spec(class_mix=(("interactive", 1.0), ("document", 1.0)))
    )
    plain = lg.generate_trace(base)
    assert [r["t"] for r in mixed] == [r["t"] for r in plain]
    assert [r["gen_len"] for r in mixed] == [r["gen_len"] for r in plain]


def test_document_class_jsonl_roundtrip(tmp_path):
    """save_trace → load_trace is lossless for document rows, and
    parse_classes speaks the CLI wire format."""
    import perf.loadgen as lg

    assert lg.parse_classes("interactive:4,document:1") == (
        ("interactive", 4.0), ("document", 1.0),
    )
    assert lg.parse_classes("document") == (("document", 1.0),)
    assert lg.parse_classes("") == ()
    spec = _doc_spec(
        class_mix=(("interactive", 1.0), ("document", 1.0))
    )
    trace = lg.generate_trace(spec)
    path = str(tmp_path / "doc.jsonl")
    lg.save_trace(path, trace, spec)
    back, spec_dict = lg.load_trace(path)
    assert back == trace
    assert spec_dict["doc_min"] == spec.doc_min
    assert tuple(map(tuple, spec_dict["class_mix"])) == spec.class_mix


# ---------------------------------------------------------------------------
# CLI refusals


def test_cli_cp_refusals(capsys):
    """--cp/--rank-page-budget refuse BY FLAG NAME on incompatible
    paths (exit 2, before any model loads) in both CLIs."""
    from perf import serve_demo
    from triton_distributed_tpu.serving import run_server

    cases = [
        (run_server.main, ["--cp", "2", "--model", "stub",
                           "--continuous"], "stub"),
        (run_server.main, ["--cp", "2", "--mode", "mega",
                           "--continuous"], "--mode mega"),
        (run_server.main, ["--rank-page-budget", "64", "--continuous"],
         "--tier-bytes"),
        (run_server.main, ["--cp", "2"], "--continuous"),
        (serve_demo.main, ["--cp", "2"], "--mode"),
        (serve_demo.main, ["--cp", "2", "--stream", "--mode", "xla",
                           "--model", "stub"], "stub"),
        (serve_demo.main, ["--rank-page-budget", "64", "--replicas",
                           "2", "--mode", "xla"], "--tier-bytes"),
    ]
    for main, argv, needle in cases:
        with pytest.raises(SystemExit) as exc:
            main(argv)
        assert exc.value.code == 2, argv
        err = capsys.readouterr().err
        assert "--cp" in err or "--rank-page-budget" in err, argv
        assert needle in err, (argv, err)
