"""Device-primitive tests: signal/wait, put_signal, barrier.

Parity: reference ``test/nvidia/test_distributed_wait.py``, ``test_notify.py``,
``tutorials/01-distributed-notify-wait.py`` — run on the simulated TPU mesh.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl


def _pcall(ctx, kernel, x, scratch_shapes, collective_id=0):
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=scratch_shapes,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=ctx.pallas_interpret(),
    )(x)


def test_ring_put_signal(ctx4):
    """Each device puts its shard to the right neighbor (parity: test_ring_put)."""

    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        dst = jax.lax.rem(me + 1, n)
        dma = dl.put_signal(x_ref, o_ref, dst, send_sem, recv_sem, axis="tp")
        dl.wait_recv(recv_sem, o_ref)  # our left neighbor's put has landed
        dma.wait_send()

    def body(x):
        return _pcall(
            ctx4, kernel, x,
            [pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        )

    f = jax.jit(ctx4.shard_map(body, in_specs=P("tp", None), out_specs=P("tp", None)))
    x = jnp.arange(4 * 128, dtype=jnp.float32).reshape(4, 128)
    out = np.asarray(f(x))
    expect = np.roll(np.asarray(x), 1, axis=0)
    np.testing.assert_allclose(out, expect)


def test_notify_wait_flag(ctx4):
    """Remote semaphore signal + wait, no data movement (parity: test_notify)."""

    def kernel(x_ref, o_ref, sem):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        # every device signals every other device once
        def body(i, _):
            peer = jax.lax.rem(me + i, n)
            dl.signal(sem, 1, dst=peer, axis="tp")
            return _
        jax.lax.fori_loop(1, n, body, None)
        dl.wait(sem, n - 1)
        o_ref[:] = x_ref[:] + 1.0

    def body(x):
        return _pcall(ctx4, kernel, x, [pltpu.SemaphoreType.REGULAR])

    f = jax.jit(ctx4.shard_map(body, in_specs=P("tp", None), out_specs=P("tp", None)))
    x = jnp.zeros((4, 128), jnp.float32)
    np.testing.assert_allclose(np.asarray(f(x)), np.ones((4, 128)))


def test_barrier_all(ctx4):
    def kernel(x_ref, o_ref):
        dl.barrier_all("tp")
        o_ref[:] = x_ref[:] * 2.0

    def body(x):
        return _pcall(ctx4, kernel, x, [])

    f = jax.jit(ctx4.shard_map(body, in_specs=P("tp", None), out_specs=P("tp", None)))
    x = jnp.ones((4, 128), jnp.float32)
    np.testing.assert_allclose(np.asarray(f(x)), 2 * np.ones((4, 128)))


def test_translate_rank(ctx2x4):
    """Device-side team translation (parity: nvshmem_team_translate_pe).

    On the 2x4 dp×tp mesh: tp-peer r of a device keeps the device's dp
    coordinate, so its world rank is dp*4 + r; translating from the
    world team back to tp extracts the tp coordinate.
    """
    def body():
        r = jnp.int32(2)
        world = dl.translate_rank(r, "tp", ("dp", "tp"))
        back = dl.translate_rank(world, ("dp", "tp"), "tp")
        me_world = dl.translate_rank(dl.rank("tp"), "tp", ("dp", "tp"))
        return jnp.stack([world, back, me_world])[None]

    f = ctx2x4.shard_map(body, in_specs=(), out_specs=P(("dp", "tp")))
    out = np.asarray(f()).reshape(8, 3)
    for w in range(8):
        dp, tp = divmod(w, 4)
        assert out[w, 0] == dp * 4 + 2      # tp-peer 2's world rank
        assert out[w, 1] == 2               # round-trip back to tp team
        assert out[w, 2] == w               # own tp rank → own world rank


def test_team_rank_tuple(ctx2x4):
    """Axis-tuple team identity (parity: nvshmem_team_my_pe / n_pes,
    ``libnvshmem_device.py:130,1199``): rank over ("dp","tp") is the
    row-major world rank; num_ranks is the team size."""
    def body():
        me = dl.team_my_pe(("dp", "tp"))
        n = jnp.int32(dl.team_n_pes(("dp", "tp")))
        return jnp.stack([me, n])[None]

    out = np.asarray(ctx2x4.shard_map(body, in_specs=(), out_specs=P(("dp", "tp")))())
    out = out.reshape(8, 2)
    np.testing.assert_array_equal(out[:, 0], np.arange(8))
    np.testing.assert_array_equal(out[:, 1], 8)


def test_signal_set_wait_until(ctx4):
    """SET-mode value-carrying signal + cmp wait (parity:
    ``nvshmemx_signal_op(..., SIGNAL_SET)`` + ``signal_wait_until``,
    ``libnvshmem_device.py:756-804``).

    Two single-set phases per device, left-neighbor publisher: phase 1
    publishes ``10 + me`` (wait eq), phase 2 publishes ``20 + me``
    (wait ge). Each phase owns its flag slot + semaphore — same-path
    puts may land out of order, so a shared slot would let phase 2's
    set satisfy phase 1's wait and deadlock phase 2 (the reason the
    reference double-buffers LL flags by call count; see the
    ``wait_until`` docstring).
    """

    def kernel(o_ref, flag1, flag2, stage_ref, send_sem, recv1, recv2):
        me = dl.rank("tp")
        n = dl.num_ranks("tp")
        right = jax.lax.rem(me + 1, n)
        left = jax.lax.rem(me - 1 + n, n)
        dl.barrier_all("tp")  # peers' flag buffers allocated
        # Phase 1: set right's flag to 10 + me, so each rank's own flag
        # arrives as 10 + left.
        dma1 = dl.signal_set(
            10 + me, stage_ref, flag1, right, send_sem, recv1, "tp"
        )
        got1 = dl.wait_until(flag1, recv1, 10 + left, cmp="eq")
        dma1.wait_send()
        # Phase 2: fresh slot; wait is a ge.
        dma2 = dl.signal_set(
            20 + me, stage_ref, flag2, right, send_sem, recv2, "tp"
        )
        got2 = dl.wait_until(flag2, recv2, 20, cmp="ge")
        dma2.wait_send()
        o_ref[0, 0] = got1
        o_ref[0, 1] = got2

    def body():
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((1, 2), jnp.int32),
            out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
            scratch_shapes=[
                pltpu.VMEM((1, 1), jnp.int32),
                pltpu.VMEM((1, 1), jnp.int32),
                pltpu.VMEM((1, 1), jnp.int32),
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
                pltpu.SemaphoreType.DMA,
            ],
            compiler_params=pltpu.CompilerParams(
                has_side_effects=True, collective_id=0
            ),
            interpret=ctx4.pallas_interpret(),
        )()

    f = jax.jit(ctx4.shard_map(body, in_specs=(), out_specs=P("tp", None)))
    out = np.asarray(f())
    left = (np.arange(4) - 1) % 4
    np.testing.assert_array_equal(out[:, 0], 10 + left)
    np.testing.assert_array_equal(out[:, 1], 20 + left)
