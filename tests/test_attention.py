"""Attention kernel tests (parity: test_decode_attn.py, test_sp_decode_attn.py
— golden = dense softmax attention)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.attention import (
    distributed_flash_decode,
    flash_attention,
    flash_decode,
    gqa_decode_reference,
    mha_reference,
)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("hq,hkv", [(4, 4), (8, 2)])
def test_flash_attention(rng, causal, hq, hkv):
    b, s, d = 2, 256, 64
    q = jnp.asarray(rng.standard_normal((b, hq, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = mha_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_attention_lse(rng):
    b, h, s, d = 1, 2, 128, 64
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    out, lse = flash_attention(q, k, v, causal=True, return_lse=True, block_q=64)
    ref, ref_lse = mha_reference(q, k, v, causal=True, return_lse=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse), atol=2e-5,
                               rtol=2e-5)


def test_flash_attention_kv_offset(rng):
    """Chunked prefill: q is the tail chunk of a longer sequence."""
    b, h, d = 1, 2, 64
    s_kv, s_q = 256, 64
    q = jnp.asarray(rng.standard_normal((b, h, s_q, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, h, s_kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, h, s_kv, d)), jnp.float32)
    off = s_kv - s_q
    out = flash_attention(q, k, v, causal=True, kv_offset=off, block_q=64)
    ref = mha_reference(q, k, v, causal=True, kv_offset=off)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("kv_len", [1, 100, 512])
def test_flash_decode(rng, kv_len):
    b, hq, hkv, s, d = 2, 8, 2, 512, 64
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    lens = jnp.full((b,), kv_len, jnp.int32)
    out = flash_decode(q, k, v, lens, chunk_k=128)
    ref = gqa_decode_reference(q, k, v, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("method", ["xla", "pallas"])
def test_distributed_flash_decode(ctx4, rng, method):
    """KV cache sequence-sharded over 4 devices; cross-rank LSE combine."""
    b, hq, hkv, s, d = 2, 4, 2, 512, 64
    q = jnp.asarray(rng.standard_normal((b, hq, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, hkv, s, d)), jnp.float32)
    lens = jnp.asarray([300, 47], jnp.int32)

    f = ctx4.shard_map(
        functools.partial(
            distributed_flash_decode, axis="tp", chunk_k=64, method=method,
            ctx=ctx4,
        ),
        in_specs=(P(), P(None, None, "tp", None), P(None, None, "tp", None), P()),
        out_specs=P(),
    )
    out = f(q, k, v, lens)
    ref = gqa_decode_reference(q, k, v, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
