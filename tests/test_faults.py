"""Chaos suite: deterministic fault injection against the serving stack.

Acceptance bar (ISSUE 3): for every `FaultPlan` seam — pool
exhaustion, decode-step exceptions, NaN logits, oversized requests,
client disconnects — the engine completes the remaining requests, the
failed request returns a STRUCTURED error with its partial output, and
the pool/radix audit reports zero leaked/double-owned pages afterward;
the server answers `ping` throughout. The conftest autouse fixture
re-audits every engine after each test, so a leak in any recovery path
fails here, loudly.
"""

import threading
import time

import numpy as np
import pytest

from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.models.continuous import (
    ContinuousEngine,
    Request,
    RequestFailedError,
)
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.runtime.faults import (
    FaultError,
    FaultPlan,
    fault_point,
    mutate_point,
)

P_A = [5, 9, 2, 4]
P_B = [7, 1, 3, 8, 6, 2, 4, 9]


def tiny_engine(ctx, **kw):
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_length", 64)
    return model, ContinuousEngine(model, **kw)


def golden(model, prompt, gen):
    return Engine(model, temperature=0.0).serve(
        np.asarray([prompt], np.int32), gen_len=gen
    )[0, len(prompt):]


# -- FaultPlan semantics (pure host-side) --------------------------------


def test_faultplan_determinism_and_counting():
    """Same seed + same call order → identical firing pattern; `at`,
    `times`, and `match` filters behave; mutation rules transform."""

    def firings(seed):
        plan = FaultPlan(seed).on("s", prob=0.5, times=100)
        got = []
        for i in range(50):
            try:
                plan.fire("s", i=i)
            except FaultError:
                got.append(i)
        return got

    assert firings(7) == firings(7)
    assert firings(7) != firings(8)  # seeded, not constant

    plan = FaultPlan().on("x", at=(2, 4), times=2)
    hits = []
    for i in range(5):
        try:
            plan.fire("x")
        except FaultError:
            hits.append(i)
    assert hits == [1, 3]
    assert [h for _, h, _ in plan.fired] == [2, 4]

    plan = FaultPlan().on("y", at=1, step=3)  # match filter on ctx
    plan.fire("y", step=0)  # hit 1 but step mismatch → no fire
    with pytest.raises(FaultError):
        FaultPlan().on("z", at=1).fire("z")

    plan = FaultPlan().on("m", at=2, times=5, mutate=lambda v, ctx: v + 1)
    assert plan.mutate("m", 10) == 10   # hit 1: untouched
    assert plan.mutate("m", 10) == 11   # hit 2: mutated

    # A ctx key colliding with the telemetry event's own fields (or
    # emit's positional ``kind``) must not TypeError out of the
    # injection site — the ctx value survives under a ctx_ prefix.
    with pytest.raises(FaultError):
        FaultPlan().on("c", at=1).fire("c", hit="ctx-collides",
                                       kind="timeout")
    from triton_distributed_tpu.obs import events as obs_events
    ev = [e for e in obs_events.default_ring().tail(0)[0]
          if e.kind == "fault" and e.fields.get("seam") == "c"]
    if ev:  # ring enabled in this run
        assert ev[-1].fields["ctx_kind"] == "timeout"
        assert ev[-1].fields["ctx_hit"] == "ctx-collides"
        assert ev[-1].fields["hit"] == 1
    plan2 = FaultPlan().on("m2", at=1, mutate=lambda v, ctx: v * 2)
    assert plan2.mutate("m2", 3, kind="k", hit="h") == 6  # no TypeError


def test_fault_points_inert_without_plan():
    fault_point("engine.decode", step=0)
    assert mutate_point("engine.logits", 42) == 42
    with FaultPlan().on("only.this", at=1):
        fault_point("engine.decode", step=0)  # unarmed seam: no-op


def test_faultplan_nested_activation_refused():
    with FaultPlan():
        with pytest.raises(RuntimeError, match="already active"):
            FaultPlan().__enter__()


# -- engine chaos: every seam leaves a clean, serviceable engine ---------


def test_pool_exhaustion_isolated(ctx4):
    """An injected pool-exhaustion failure at admission fails ONLY that
    request; the others complete bit-exact and the audit is clean."""
    model, eng = tiny_engine(ctx4, max_batch=1)
    gold_a = golden(model, P_A, 4)
    reqs = [(np.asarray(P_A, np.int32), 4)] * 3
    with FaultPlan().exhaust_pool(at=2):  # 2nd admission's allocate
        results = eng.run(reqs, results=True)
    statuses = [r.status for r in results]
    assert statuses.count("failed") == 1
    assert statuses.count("ok") == 2
    for r in results:
        if r.ok:
            np.testing.assert_array_equal(r.tokens, gold_a)
        else:
            assert "exhausted" in r.reason
            assert len(r.tokens) == 0  # failed before its first token
    assert eng.audit() == []
    assert len(eng.pool.free) == eng._capacity
    # Engine reusable after the fault: a clean run matches the golden.
    np.testing.assert_array_equal(eng.run([(P_A, 4)])[0], gold_a)


def test_decode_exception_slot_attributed(ctx4):
    """A decode fault carrying slot attribution evicts exactly that
    request (partial output, structured error); its batchmate's greedy
    stream is untouched."""
    model, eng = tiny_engine(ctx4)
    gold_b = golden(model, P_B, 6)
    with FaultPlan().decode_exc(at=3, slot=0):
        results = eng.run(
            [(np.asarray(P_A, np.int32), 6),
             (np.asarray(P_B, np.int32), 6)],
            results=True,
        )
    assert results[0].status == "failed"
    assert "injected" in results[0].reason
    assert 0 < len(results[0].tokens) < 6  # partial output survived
    assert results[1].ok
    np.testing.assert_array_equal(results[1].tokens, gold_b)
    assert eng.last_stats["decode_faults"] == 1
    assert eng.audit() == []


def test_decode_exception_unattributed_poisons_batch(ctx4):
    """A decode fault with NO slot attribution fails every in-flight
    request — but queued requests still serve and the engine stays
    clean."""
    model, eng = tiny_engine(ctx4, max_batch=1)
    gold_a = golden(model, P_A, 4)
    with FaultPlan().decode_exc(at=2):
        results = eng.run(
            [(np.asarray(P_A, np.int32), 4),
             (np.asarray(P_A, np.int32), 4)],
            results=True,
        )
    assert results[0].status == "failed"
    assert results[1].ok  # admitted after the fault, served normally
    np.testing.assert_array_equal(results[1].tokens, gold_a)
    assert eng.audit() == []


def test_nan_logits_guard(ctx4):
    """Injected NaN logits fail only the poisoned slot (structured
    `nan_logits`, counted in last_stats) — never silently sampled."""
    model, eng = tiny_engine(ctx4)
    gold_b = golden(model, P_B, 6)
    with FaultPlan().nan_logits(at=2, slot=0):
        results = eng.run(
            [(np.asarray(P_A, np.int32), 6),
             (np.asarray(P_B, np.int32), 6)],
            results=True,
        )
    assert results[0].status == "nan_logits"
    assert "non-finite" in results[0].reason
    err = results[0].error  # structured RequestError channel
    assert err is not None and err.status == "nan_logits"
    assert results[1].ok and results[1].error is None
    np.testing.assert_array_equal(results[1].tokens, gold_b)
    assert eng.last_stats["nonfinite_logits"] == 1
    assert eng.audit() == []


def test_oversized_request_isolated(ctx4):
    """A request that can never fit gets a structured `unservable`
    result (results mode) while the rest of the batch serves; legacy
    mode still raises ValueError up front."""
    model, eng = tiny_engine(ctx4)
    gold_a = golden(model, P_A, 4)
    results = eng.run(
        [(np.asarray(P_A, np.int32), 4),
         (np.zeros(60, np.int32), 16)],  # 76 > max_length 64
        results=True,
    )
    assert results[0].ok
    np.testing.assert_array_equal(results[0].tokens, gold_a)
    assert results[1].status == "unservable"
    assert "exceeds max_length" in results[1].reason
    with pytest.raises(ValueError, match="exceeds max_length"):
        eng.run([(np.zeros(60, np.int32), 16)])
    assert eng.audit() == []


def test_deadline_and_load_shedding(ctx4):
    """deadline_s=0 expires before admission (structured
    `deadline_exceeded`); max_queue sheds excess load as `overloaded`;
    the surviving request is unaffected."""
    model, eng = tiny_engine(ctx4, max_batch=1, max_queue=2)
    gold_a = golden(model, P_A, 4)
    results = eng.run(
        [
            Request(np.asarray(P_A, np.int32), 4),
            Request(np.asarray(P_A, np.int32), 4, deadline_s=0.0),
            Request(np.asarray(P_A, np.int32), 4),  # beyond max_queue=2
        ],
        results=True,
    )
    assert results[0].ok
    np.testing.assert_array_equal(results[0].tokens, gold_a)
    assert results[1].status == "deadline_exceeded"
    assert results[2].status == "overloaded"
    assert "retry" in results[2].reason
    stats = eng.last_stats
    assert stats["deadline_expired"] == 1
    assert stats["shed_requests"] == 1
    assert eng.audit() == []


def test_legacy_run_raises_structured_failure(ctx4):
    """run(results=False) finishes the survivors, tears the failure
    down cleanly, and raises RequestFailedError carrying it."""
    model, eng = tiny_engine(ctx4)
    with FaultPlan().nan_logits(at=2, slot=0):
        with pytest.raises(RequestFailedError, match="nan_logits"):
            eng.run([(np.asarray(P_A, np.int32), 6),
                     (np.asarray(P_B, np.int32), 6)])
    assert eng.audit() == []


def test_prefix_cache_fault_isolation(ctx4):
    """Faults on a prefix-cache engine release every pin: a failed
    admission drops its match refcounts and the tree/pool partition
    stays exact (the leak this PR exists to catch)."""
    model, eng = tiny_engine(
        ctx4, prefix_cache=True, num_pages=12
    )
    warm = np.asarray(P_B * 3, np.int32)  # 24 tokens: populates the tree
    eng.run([(warm, 4)])
    assert eng.prefix.node_count > 0
    with FaultPlan().admit_exc(at=1):
        results = eng.run(
            [(warm, 4), (np.asarray(P_A, np.int32), 4)], results=True
        )
    assert results[0].status == "failed"
    assert results[1].ok
    assert eng.audit() == []
    assert all(n.refcount == 0 for n in eng.prefix.walk())
    # The tree survived the fault: a clean warm run still hits it.
    out = eng.run([(warm, 4)], results=True)
    assert out[0].ok and eng.last_stats["prefix_hit_tokens"] > 0


def test_pool_exhaustion_mid_prefix_admission(ctx4):
    """Pool exhaustion raised INSIDE prefix admission (after the match
    pinned tree nodes) must release those pins on the failure path."""
    model, eng = tiny_engine(
        ctx4, prefix_cache=True, num_pages=12
    )
    warm = np.asarray(P_B * 3, np.int32)
    eng.run([(warm, 4)])
    with FaultPlan().exhaust_pool(at=1):
        results = eng.run([(warm, 4)], results=True)
    assert results[0].status == "failed"
    assert "exhausted" in results[0].reason
    assert eng.audit() == []
    assert all(n.refcount == 0 for n in eng.prefix.walk())


def test_spec_verify_fault_isolated(ctx4):
    """A speculative verify that raises fails only its own request;
    the engine then serves the next request normally."""
    model, eng = tiny_engine(ctx4, max_batch=1, speculative=3)
    rep = np.asarray(P_A * 2, np.int32)  # repetitive → drafts fire
    gold = golden(model, list(rep), 6)
    with FaultPlan().verify_exc(at=1):
        results = eng.run([(rep, 6), (rep, 6)], results=True)
    assert results[0].status == "failed"
    assert results[1].ok
    np.testing.assert_array_equal(results[1].tokens, gold)
    assert eng.audit() == []


def test_spec_verify_nan_logits_guarded(ctx4):
    """Non-finite logits inside a speculative verify chunk must fail
    that request with a structured `nan_logits` (counted), never be
    silently argmax'd into accepted tokens."""
    import numpy as _np

    model, eng = tiny_engine(ctx4, max_batch=1, speculative=3)
    rep = np.asarray(P_A * 2, np.int32)
    gold = golden(model, list(rep), 6)

    def nanify(value, _ctx):
        value = _np.array(value, _np.float32)
        value[0] = _np.nan
        return value

    with FaultPlan().on("spec.logits", at=1, mutate=nanify):
        results = eng.run([(rep, 6), (rep, 6)], results=True)
    assert results[0].status == "nan_logits"
    assert results[1].ok
    np.testing.assert_array_equal(results[1].tokens, gold)
    assert eng.last_stats["nonfinite_logits"] == 1
    assert eng.audit() == []


def test_engine_reusable_after_fault_storm(ctx4):
    """One engine, three different fault runs back to back, then a
    clean run: output bit-exact, zero leaked pages — the crash-safe
    teardown really is crash-safe."""
    model, eng = tiny_engine(ctx4, max_batch=1)
    gold_a = golden(model, P_A, 4)
    for plan in (
        FaultPlan().exhaust_pool(at=1),
        FaultPlan().decode_exc(at=1),
        FaultPlan().nan_logits(at=1, slot=0),
    ):
        with plan:
            results = eng.run([(np.asarray(P_A, np.int32), 4)],
                              results=True)
        assert not results[0].ok
        assert eng.audit() == []
        assert len(eng.pool.free) == eng._capacity
    np.testing.assert_array_equal(eng.run([(P_A, 4)])[0], gold_a)


def test_all_deadlines_expire_with_queued_request(ctx4):
    """Regression: the active request expires mid-decode AND the queued
    request's deadline is already gone — run() must return two
    structured deadline_exceeded results, not crash popping an empty
    queue after _try_admit drained it."""
    model, eng = tiny_engine(ctx4, max_batch=1)
    results = eng.run(
        [
            Request(np.asarray(P_A, np.int32), 48, deadline_s=0.2),
            Request(np.asarray(P_A, np.int32), 4, deadline_s=0.0),
        ],
        results=True,
    )
    assert [r.status for r in results] == ["deadline_exceeded"] * 2
    assert eng.audit() == []


def test_server_recv_fault_counted(ctx4):
    """Regression: a raise-style fault on the server.recv seam (a
    RuntimeError, not an OSError) must be absorbed by the connection
    thread AND counted as a conn error — never a silent thread death."""
    from triton_distributed_tpu.serving import ModelServer, request

    model, eng = tiny_engine(ctx4)
    server = ModelServer(eng).start()
    try:
        with FaultPlan().on("server.recv", at=1):
            with pytest.raises((ConnectionError, OSError)):
                request(server.host, server.port, {"cmd": "ping"},
                        timeout=5)
        assert request(server.host, server.port, {"cmd": "ping"})["ok"]
        stats = request(server.host, server.port, {"cmd": "stats"})
        assert stats["stats"]["server"]["conn_errors"] >= 1
    finally:
        server.shutdown()


# -- server chaos --------------------------------------------------------


def test_server_serviceable_through_chaos(ctx4):
    """The acceptance scenario end to end: while a faulted generation
    runs, ping answers from another connection; a dropped connection
    (injected mid-response) is survived + counted, and the client-side
    retry/backoff recovers; per-request failures ride the structured
    results channel."""
    from triton_distributed_tpu.serving import ModelServer, request

    model, eng = tiny_engine(ctx4)
    server = ModelServer(eng).start()
    try:
        pings: list[bool] = []
        stop = threading.Event()

        def prober():
            while not stop.is_set():
                try:
                    pings.append(request(
                        server.host, server.port, {"cmd": "ping"},
                        timeout=5.0,
                    )["ok"])
                except Exception:
                    pings.append(False)
                time.sleep(0.01)

        t = threading.Thread(target=prober, daemon=True)
        # Phase 1: NaN fault mid-generation, pings probing concurrently
        # (they bypass the engine lock, so they answer mid-payload).
        with FaultPlan().nan_logits(at=2, slot=0):
            t.start()
            resp = request(
                server.host, server.port,
                {"requests": [P_A, P_B], "gen_lens": [6, 6]},
            )
            statuses = [r["status"] for r in resp["results"]]
            assert statuses[0] == "nan_logits" and statuses[1] == "ok"
            stop.set()
            t.join(timeout=5)
        assert pings and all(pings)  # ping answered THROUGHOUT
        # Phase 2: the next response write is dropped mid-stream (no
        # prober — the injection counts raw sends); the client-side
        # retry/backoff recovers on a fresh connection.
        with FaultPlan().drop_connection(at=1):
            resp2 = request(
                server.host, server.port,
                {"requests": [P_A], "gen_lens": [2]},
                retries=3, backoff_s=0.05,
            )
        assert resp2["results"][0]["status"] == "ok"
        stats = request(server.host, server.port, {"cmd": "stats"})
        assert stats["stats"]["server"]["conn_errors"] >= 1
        assert eng.audit() == []
    finally:
        server.shutdown()


def test_server_deadline_payload(ctx4):
    """deadline_s rides the requests payload down to the engine."""
    from triton_distributed_tpu.serving import ModelServer, request

    model, eng = tiny_engine(ctx4)
    server = ModelServer(eng).start()
    try:
        resp = request(
            server.host, server.port,
            {"requests": [P_A, P_A], "gen_lens": [4, 4],
             "deadline_s": [None, 0.0]},
        )
        assert resp["results"][0]["status"] == "ok"
        assert resp["results"][1]["status"] == "deadline_exceeded"
    finally:
        server.shutdown()


def test_chaos_counters_and_events_fire(ctx4, fresh_telemetry):
    """ISSUE 5 satellite: chaos scenarios leave matching telemetry —
    the shed/deadline/nan counters in the metrics registry AND the
    corresponding shed/deadline/nan_guard/fault events in the ring,
    each consistent with the engine's own last_stats ledger."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.obs import metrics as obs_metrics

    model, eng = tiny_engine(ctx4, max_batch=1, max_queue=2)
    with FaultPlan().nan_logits(at=2, slot=0):
        results = eng.run(
            [
                Request(np.asarray(P_A, np.int32), 6),  # poisoned
                Request(np.asarray(P_B, np.int32), 4, deadline_s=0.0),
                Request(np.asarray(P_A, np.int32), 4),  # > max_queue
            ],
            results=True,
        )
    assert [r.status for r in results] == [
        "nan_logits", "deadline_exceeded", "overloaded"
    ]
    assert eng.audit() == []

    # Counters mirror last_stats exactly (registry cleared above).
    def val(name):
        m = obs_metrics.default_registry().get(name)
        return m.value() if m is not None else 0

    stats = eng.last_stats
    assert (val("tdt_engine_shed_requests_total")
            == stats["shed_requests"] == 1)
    assert (val("tdt_engine_deadline_expired_total")
            == stats["deadline_expired"] == 1)
    assert (val("tdt_engine_nonfinite_logits_total")
            == stats["nonfinite_logits"] == 1)
    assert (val("tdt_engine_failed_requests_total")
            == stats["failed_requests"] == 3)

    # Status-labeled request totals pick up the full taxonomy mix.
    totals = obs_metrics.default_registry().get("tdt_requests_total")
    for status in ("nan_logits", "deadline_exceeded", "overloaded"):
        assert totals.value(status=status) == 1, status

    # Events: the injected fault itself plus each failure's kind.
    evts, _ = obs_events.default_ring().tail(0)
    kinds = [e.kind for e in evts]
    assert "fault" in kinds       # runtime/faults.py activation
    assert "shed" in kinds        # overloaded
    assert "deadline" in kinds    # deadline_exceeded
    assert "nan_guard" in kinds   # nan_logits
    fault = next(e for e in evts if e.kind == "fault")
    assert fault.fields["seam"] == "engine.logits"
    # Seqs are strictly increasing — the ring is tail-consistent
    # even after a chaos run.
    seqs = [e.seq for e in evts]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
