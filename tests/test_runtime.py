"""Runtime core tests (parity: reference test_utils.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.runtime import (
    assert_allclose,
    current_context,
    init_seed,
    initialize_distributed,
    finalize_distributed,
    perf_func,
)
from jax.sharding import PartitionSpec as P


def test_initialize_basic():
    ctx = initialize_distributed(tp=8)
    assert ctx.world_size == 8
    assert ctx.axis_names == ("tp",)
    assert current_context() is ctx
    finalize_distributed()
    with pytest.raises(RuntimeError):
        current_context()


def test_initialize_dp_fill():
    ctx = initialize_distributed(tp=4)
    # remaining devices absorbed into dp
    assert ctx.axis_names == ("dp", "tp")
    assert ctx.axis_size("dp") == 2 and ctx.axis_size("tp") == 4
    finalize_distributed()


def test_axis_order_canonical():
    ctx = initialize_distributed(axes={"tp": 2, "dp": 2, "pp": 2})
    assert ctx.axis_names == ("dp", "pp", "tp")
    finalize_distributed()


def test_shard_map_collective(ctx8):
    def psum_rank(x):
        r = jax.lax.axis_index("tp").astype(jnp.float32)
        return x + jax.lax.psum(r, "tp")

    f = ctx8.shard_map(psum_rank, in_specs=P("tp"), out_specs=P("tp"))
    x = jnp.zeros((8,), jnp.float32)
    out = f(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8,), 28.0))


def test_perf_func_returns_output():
    out, ms = perf_func(lambda: jnp.ones((4,)).sum(), iters=2, warmup_iters=1)
    assert float(out) == 4.0
    assert ms >= 0.0


def test_assert_allclose_reports():
    with pytest.raises(AssertionError, match="mismatched"):
        assert_allclose(np.ones(4), np.zeros(4))
    assert_allclose(np.ones(4), np.ones(4) + 1e-6)


def test_init_seed_deterministic():
    k1 = init_seed(7)
    k2 = init_seed(7)
    assert jnp.array_equal(jax.random.uniform(k1, (3,)), jax.random.uniform(k2, (3,)))


class TestTeamSplit:
    """Parity: reference NVSHMEM team split (test_team_split.py) — a mesh
    axis splits into two named sub-axes addressable independently."""

    def test_split_axis_collectives(self, ctx8, rng):
        import functools

        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        sub = ctx8.split_axis("tp", ("tpo", "tpi"), (2, 4))
        assert sub.axis_size("tpo") == 2 and sub.axis_size("tpi") == 4
        x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)

        # psum over only the inner team must not cross outer teams.
        def body(xi):
            return jax.lax.psum(xi, "tpi")

        f = sub.shard_map(
            body, in_specs=P(("tpo", "tpi"), None), out_specs=P("tpo", None)
        )
        out = np.asarray(f(x))  # [2, 16] — one row per outer team
        xs = np.asarray(x).reshape(2, 4, 16)
        np.testing.assert_allclose(out, xs.sum(1), rtol=1e-5)

    def test_split_axis_validates(self, ctx8):
        import pytest

        with pytest.raises(ValueError, match="does not cover"):
            ctx8.split_axis("tp", ("a", "b"), (3, 2))


class TestSnakeRing:
    """ICI-aware device ordering (VERDICT #7): consecutive devices in the
    snake ring must be physical neighbors (Manhattan distance 1)."""

    @pytest.mark.parametrize("dims", [(2, 2, 2), (4, 2, 2), (4, 4), (8,), (2, 4, 2)])
    def test_neighbor_distance_one(self, dims):
        from triton_distributed_tpu.runtime.mesh import snake_ring_order

        coords = np.stack(
            [g.ravel() for g in np.meshgrid(*[np.arange(d) for d in dims], indexing="ij")],
            axis=1,
        )
        # scramble enumeration order, as a real backend might
        rng = np.random.default_rng(0)
        perm = rng.permutation(len(coords))
        order = snake_ring_order(coords[perm])
        ring = coords[perm][order]
        for a, b in zip(ring[:-1], ring[1:]):
            assert np.abs(a - b).sum() == 1, (a, b)
        # closing hop is distance 1 in exactly one dim (torus wrap or unit step)
        diff = np.abs(ring[-1] - ring[0])
        wrap = np.asarray(dims) - 1
        assert ((diff == 1) | (diff == wrap) | (diff == 0)).all()

    def test_topology_fields_cpu(self):
        ctx = initialize_distributed(tp=8)
        assert ctx.topology.torus_shape is None  # cpu: no coords
        finalize_distributed()


def test_probe_topology_and_ici(ctx4):
    """Probe suite (parity: reference topology/bandwidth probes,
    utils.py:592-867): static summary everywhere, ICI probe runs the
    ring permute (memcpy-rate on the sim mesh, ICI on hardware)."""
    from triton_distributed_tpu.runtime.probe import (
        measure_ici_bandwidth_gbs,
        probe_topology,
    )

    info = probe_topology(ctx4)
    assert info["mesh"] == {"tp": 4}
    assert info["platform"] == "cpu"
    assert info["spec"]["hbm_gbs"] > 0
    assert "measured" not in info  # HBM probe is TPU-only

    gbs = measure_ici_bandwidth_gbs("tp", nbytes=64 * 1024, iters=2, ctx=ctx4)
    assert gbs > 0


def test_axis_ici_vs_dcn_classification(ctx2x4):
    """DCN-spanning axes must be detected (AUTO dispatch falls back to
    XLA there — device-initiated DMA is ICI-only). Classification is by
    SLICE id, never process id: ICI spans hosts inside one slice (a
    v4-32 has 4 processes and one all-ICI slice). The pure classifier
    is exercised with synthetic slice-id grids."""
    import numpy as np

    from triton_distributed_tpu.runtime.mesh import DistContext

    # 2 slices x 4 chips: slice id differs along dim 0 (DCN axis),
    # constant along dim 1 (ICI axis).
    ids = np.array([[0, 0, 0, 0], [1, 1, 1, 1]])
    assert not DistContext._axis_within_group(ids, 0)  # dcn axis
    assert DistContext._axis_within_group(ids, 1)      # tp axis

    # 4 slices of 4 chips over a (4, 4) mesh.
    ids = np.repeat(np.arange(4)[:, None], 4, axis=1)
    assert not DistContext._axis_within_group(ids, 0)
    assert DistContext._axis_within_group(ids, 1)

    # Live sim-mesh context (CPU devices carry no slice_index → one
    # slice): every axis is ICI even though a multi-host pod would have
    # several processes.
    assert ctx2x4.axis_is_ici("tp") and ctx2x4.axis_is_ici("dp")


class TestGroupProfileMerge:
    """One-file merged timeline (parity: reference group_profile's
    per-rank chrome-trace gather + pid remap + merge,
    ``utils.py:505-589``)."""

    @staticmethod
    def _write_rank_trace(root, rank, pid, name, session="session1",
                          mtime=None, empty=False):
        import gzip
        import json
        import os

        d = root / f"rank{rank}" / "plugins" / "profile" / session
        d.mkdir(parents=True)
        if not empty:
            trace = {
                "displayTimeUnit": "ns",
                "traceEvents": [
                    {"ph": "M", "name": "process_name", "pid": pid,
                     "args": {"name": name}},
                    {"ph": "X", "name": f"op_r{rank}", "pid": pid,
                     "tid": 1, "ts": 10 * rank, "dur": 5},
                ],
            }
            with gzip.open(
                os.path.join(d, "host.trace.json.gz"), "wt"
            ) as f:
                json.dump(trace, f)
        if mtime is not None:
            os.utime(d, (mtime, mtime))

    def test_merges_ranks_into_one_file(self, tmp_path):
        import gzip
        import json

        from triton_distributed_tpu.runtime.profiling import (
            merge_group_profile,
        )

        root = tmp_path / "prof" / "myrun"
        self._write_rank_trace(root, 0, 7, "tpu_driver")
        self._write_rank_trace(root, 1, 7, "tpu_driver")
        out = merge_group_profile("myrun", str(tmp_path / "prof"))
        assert out is not None and out.endswith("merged.trace.json.gz")
        with gzip.open(out, "rt") as f:
            merged = json.load(f)
        evs = merged["traceEvents"]
        # Both ranks' events present, pids namespaced apart.
        pids = {e["pid"] for e in evs}
        assert len(pids) == 2
        names = {e["args"]["name"] for e in evs if e.get("ph") == "M"}
        assert names == {"rank0: tpu_driver", "rank1: tpu_driver"}
        assert merged["displayTimeUnit"] == "ns"

    def test_missing_traces_returns_none(self, tmp_path):
        from triton_distributed_tpu.runtime.profiling import (
            merge_group_profile,
        )

        assert merge_group_profile("nothing", str(tmp_path)) is None

    def test_newest_session_by_mtime_not_name(self, tmp_path):
        """A stale session whose NAME sorts last must lose to the
        mtime-newest one, and a session whose export failed (no trace
        file) must be skipped for the newest COMPLETE session
        (ADVICE r4)."""
        import gzip
        import json

        from triton_distributed_tpu.runtime.profiling import (
            merge_group_profile,
        )

        root = tmp_path / "prof" / "run"
        # "zzz_stale" sorts lexicographically after "fresh" but is old.
        self._write_rank_trace(root, 0, 1, "stale", session="zzz_stale",
                               mtime=1000.0)
        self._write_rank_trace(root, 0, 1, "fresh", session="fresh",
                               mtime=2000.0)
        # Newest session of all has NO trace (failed export): skipped.
        self._write_rank_trace(root, 0, 1, "broken", session="broken",
                               mtime=3000.0, empty=True)
        out = merge_group_profile("run", str(tmp_path / "prof"))
        with gzip.open(out, "rt") as f:
            merged = json.load(f)
        names = {e["args"]["name"] for e in merged["traceEvents"]
                 if e.get("ph") == "M"}
        assert names == {"rank0: fresh"}

    def test_warns_on_mixed_sessions_across_ranks(self, tmp_path):
        import warnings as _w

        from triton_distributed_tpu.runtime.profiling import (
            merge_group_profile,
        )

        root = tmp_path / "prof" / "run"
        self._write_rank_trace(root, 0, 1, "a", session="sessA")
        self._write_rank_trace(root, 1, 1, "b", session="sessB")
        with _w.catch_warnings(record=True) as caught:
            _w.simplefilter("always")
            out = merge_group_profile("run", str(tmp_path / "prof"))
        assert out is not None  # merge proceeds anyway
        assert any("different capture sessions" in str(w.message)
                   for w in caught)

    def test_warns_on_mixed_layouts_across_ranks(self, tmp_path):
        """One rank resolved via a session dir, another via the flat
        ``*.trace.json.gz`` fallback: the flat rank records the
        ``<flat>`` sentinel session, so the layout mix trips the same
        mixed-sessions warning (ADVICE r5)."""
        import gzip
        import json
        import warnings as _w

        from triton_distributed_tpu.runtime.profiling import (
            merge_group_profile,
        )

        root = tmp_path / "prof" / "run"
        self._write_rank_trace(root, 0, 1, "sessioned", session="sessA")
        # rank1: flat layout, no plugins/profile dir.
        flat_dir = root / "rank1"
        flat_dir.mkdir(parents=True)
        trace = {"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "flat"}},
        ]}
        with gzip.open(str(flat_dir / "host.trace.json.gz"), "wt") as f:
            json.dump(trace, f)
        with _w.catch_warnings(record=True) as caught:
            _w.simplefilter("always")
            out = merge_group_profile("run", str(tmp_path / "prof"))
        assert out is not None
        assert any("different capture sessions" in str(w.message)
                   for w in caught)

    def test_pid_remap_collision_bounds(self, tmp_path):
        """ISSUE 8 satellite: rank pid namespacing must be collision-
        free up to the stride — a pid just under ``_PID_STRIDE`` on
        rank r must stay strictly below rank r+1's namespace, and the
        device-task pid offset (obs/kernel_trace.DEVICE_TASK_PID) must
        sit inside the stride too."""
        import gzip
        import json

        from triton_distributed_tpu.obs.kernel_trace import (
            DEVICE_TASK_PID,
        )
        from triton_distributed_tpu.runtime.profiling import (
            _PID_STRIDE,
            merge_group_profile,
        )

        assert 0 < DEVICE_TASK_PID < _PID_STRIDE
        root = tmp_path / "prof" / "run"
        # Rank 0 with the largest in-stride pid, rank 1 with pid 0.
        self._write_rank_trace(root, 0, _PID_STRIDE - 1, "hi")
        self._write_rank_trace(root, 1, 0, "lo")
        out = merge_group_profile("run", str(tmp_path / "prof"))
        with gzip.open(out, "rt") as f:
            merged = json.load(f)
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {_PID_STRIDE - 1, _PID_STRIDE}
        # Distinct namespaces: every rank-0 pid < every rank-1 pid.
        assert max(p for p in pids if p < _PID_STRIDE) < _PID_STRIDE

    def test_missing_and_malformed_rank_dirs_tolerated(self, tmp_path):
        """ISSUE 8 satellite: a rank dir with no usable trace, a
        non-numeric ``rankX`` dir, and a GAP in rank numbering must all
        be skipped — the merge still emits the ranks it can read."""
        import gzip
        import json

        from triton_distributed_tpu.runtime.profiling import (
            merge_group_profile,
        )

        root = tmp_path / "prof" / "run"
        self._write_rank_trace(root, 0, 1, "good0")
        # Rank 1 missing entirely (gap); rank 2 present.
        self._write_rank_trace(root, 2, 1, "good2")
        # A rank dir with an empty session (no exported trace).
        self._write_rank_trace(root, 3, 1, "broken", empty=True)
        # A dir that parses as no rank at all.
        (root / "rank_bogus").mkdir()
        (root / "rankX7").mkdir()
        out = merge_group_profile("run", str(tmp_path / "prof"))
        with gzip.open(out, "rt") as f:
            merged = json.load(f)
        names = {e["args"]["name"] for e in merged["traceEvents"]
                 if e.get("ph") == "M"}
        assert names == {"rank0: good0", "rank2: good2"}

    def test_merged_gzip_round_trip(self, tmp_path):
        """ISSUE 8 satellite: the merged file must be a REAL gzip that
        round-trips through a fresh load — including a re-merge over
        the directory that now contains the merged file itself (the
        merged output must not be picked up as a rank trace)."""
        import gzip
        import json

        from triton_distributed_tpu.runtime.profiling import (
            merge_group_profile,
        )

        root = tmp_path / "prof" / "run"
        self._write_rank_trace(root, 0, 5, "p")
        self._write_rank_trace(root, 1, 5, "p")
        out = merge_group_profile("run", str(tmp_path / "prof"))
        with open(out, "rb") as f:
            assert f.read(2) == b"\x1f\x8b"  # gzip magic
        with gzip.open(out, "rt") as f:
            first = json.load(f)
        # Re-merge with the merged.trace.json.gz already on disk:
        # event set must be identical (no self-ingestion).
        out2 = merge_group_profile("run", str(tmp_path / "prof"))
        with gzip.open(out2, "rt") as f:
            second = json.load(f)
        assert first["traceEvents"] == second["traceEvents"]
        assert len(first["traceEvents"]) == 4  # 2 ranks × (M + X)

    def test_group_profile_end_to_end_merge(self, tmp_path):
        """A real single-process capture must leave ONE merged file next
        to the per-rank dir."""
        import os

        from triton_distributed_tpu.runtime.profiling import group_profile

        ctx = initialize_distributed(tp=2)
        try:
            with group_profile("e2e", out_dir=str(tmp_path)):
                x = jnp.ones((64, 64))
                np.asarray(jax.jit(lambda v: v @ v)(x))
        finally:
            finalize_distributed()
        merged = tmp_path / "e2e" / "merged.trace.json.gz"
        assert os.path.exists(merged), (
            "no merged timeline; rank dirs: "
            + str(list((tmp_path / 'e2e').iterdir()))
        )
