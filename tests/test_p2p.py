"""Pipeline p2p transport tests (parity: reference test_pp.py — send a
tensor stage→stage and check arrival)."""

import functools

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.parallel import pp_send_recv, pp_shift


@pytest.mark.parametrize("method", ["xla", "pallas"])
@pytest.mark.parametrize("wrap", [False, True])
def test_pp_shift(ctx4, rng, method, wrap):
    n = 4
    x = jnp.asarray(rng.standard_normal((n, 8, 128)), jnp.float32)

    f = ctx4.shard_map(
        functools.partial(pp_shift, axis="tp", wrap=wrap, method=method,
                          ctx=ctx4),
        in_specs=P("tp"),
        out_specs=P("tp"),
    )
    out = np.asarray(f(x))  # [n, 8, 128] — row i = stage i's received buf
    xs = np.asarray(x)
    for i in range(n):
        if i == 0 and not wrap:
            np.testing.assert_array_equal(out[0], 0)
        else:
            np.testing.assert_array_equal(out[i], xs[(i - 1) % n])


def test_pp_send_recv(ctx4, rng):
    x = jnp.asarray(rng.standard_normal((4, 8, 16)), jnp.float32)
    f = ctx4.shard_map(
        functools.partial(pp_send_recv, src=1, dst=3, axis="tp"),
        in_specs=P("tp"),
        out_specs=P("tp"),
    )
    out = np.asarray(f(x))
    np.testing.assert_array_equal(out[3], np.asarray(x)[1])
    np.testing.assert_array_equal(out[0], 0)
