"""Sequence-parallel attention tests (parity: reference
test_sp_ag_attention_intra_node.py — golden = dense causal attention over
the full gathered sequence)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.attention import (
    mha_reference,
    ring_attention,
    sp_ag_attention,
)


def _make(rng, hq, hkv, s, hd):
    q = jnp.asarray(rng.standard_normal((hq, s, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((hkv, s, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((hkv, s, hd)), jnp.float32)
    return q, k, v


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_sp_ag_attention(ctx4, rng, hq, hkv):
    s, hd = 256, 64  # 64 rows per device
    q, k, v = _make(rng, hq, hkv, s, hd)

    f = ctx4.shard_map(
        functools.partial(sp_ag_attention, axis="tp", block_q=32, ctx=ctx4),
        in_specs=(P(None, "tp", None),) * 3,
        out_specs=P(None, "tp", None),
    )
    out = f(q, k, v)
    ref = mha_reference(q[None], k[None], v[None], causal=True)[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention(ctx4, rng, causal):
    s, hq, hkv, hd = 256, 4, 2, 64
    q, k, v = _make(rng, hq, hkv, s, hd)

    f = ctx4.shard_map(
        functools.partial(ring_attention, axis="tp", causal=causal, block_q=64,
                          block_k=64),
        in_specs=(P(None, "tp", None),) * 3,
        out_specs=P(None, "tp", None),
    )
    out = f(q, k, v)
    ref = mha_reference(q[None], k[None], v[None], causal=causal)[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.parametrize("method", ["xla", "pallas"])
def test_sp_decode_attention(ctx4, rng, method):
    """Append a token into the sequence-sharded cache, then attend.
    Parity: reference test_sp_decode_attn.py."""
    from triton_distributed_tpu.layers.sp_flash_decode import sp_decode_attention
    from triton_distributed_tpu.ops.attention import gqa_decode_reference

    b, hq, hkv, s, hd = 2, 4, 2, 256, 64
    q = jnp.asarray(rng.standard_normal((b, hq, hd)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.float32)
    kn = jnp.asarray(rng.standard_normal((b, hkv, hd)), jnp.float32)
    vn = jnp.asarray(rng.standard_normal((b, hkv, hd)), jnp.float32)
    lens = jnp.asarray([100, 37], jnp.int32)

    f = ctx4.shard_map(
        functools.partial(
            sp_decode_attention, axis="tp", chunk_k=64, method=method, ctx=ctx4
        ),
        in_specs=(P(), P(), P(), P(None, None, "tp", None),
                  P(None, None, "tp", None), P()),
        out_specs=(P(), P(None, None, "tp", None), P(None, None, "tp", None)),
    )
    out, kc2, vc2 = f(q, kn, vn, kc, vc, lens)

    # Golden: cache with the new token written at kv_len[b].
    kg, vg = np.array(kc), np.array(vc)
    for i in range(b):
        kg[i, :, int(lens[i])] = np.asarray(kn[i])
        vg[i, :, int(lens[i])] = np.asarray(vn[i])
    np.testing.assert_allclose(np.asarray(kc2), kg, atol=0, rtol=0)
    ref = gqa_decode_reference(q, jnp.asarray(kg), jnp.asarray(vg), lens + 1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2)])
def test_sp_ag_attention_2level(ctx2x4, rng, hq, hkv):
    """DCN×ICI two-level SP attention vs dense causal golden (parity:
    reference test_sp_ag_attention_inter_node.py)."""
    from triton_distributed_tpu.ops.attention import sp_ag_attention_2level

    # Small: 8 interpret devices share one CPU core and big per-device
    # buffers starve the XLA client (see conftest).
    s, hd = 128, 32  # 2 slices × 4 ranks → 16 rows per device
    q, k, v = _make(rng, hq, hkv, s, hd)

    f = ctx2x4.shard_map(
        functools.partial(
            sp_ag_attention_2level, inner_axis="tp", outer_axis="dp",
            block_q=16, ctx=ctx2x4,
        ),
        in_specs=(P(None, ("dp", "tp"), None),) * 3,
        out_specs=P(None, ("dp", "tp"), None),
    )
    out = f(q, k, v)
    ref = mha_reference(q[None], k[None], v[None], causal=True)[0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)


@pytest.mark.parametrize("method", ["xla", "pallas"])
def test_distributed_flash_decode_2level(ctx2x4, rng, method):
    """Two-level (DCN×ICI) decode merge vs dense golden (parity:
    reference flash-decode multi-node scaling, README.md:202-209)."""
    from triton_distributed_tpu.ops.attention import (
        distributed_flash_decode_2level,
        gqa_decode_reference,
    )

    b, hq, hkv, s, hd = 2, 4, 2, 256, 64  # 8 shards × 32 positions
    q = jnp.asarray(rng.standard_normal((b, hq, hd)), jnp.float32)
    kc = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.float32)
    vc = jnp.asarray(rng.standard_normal((b, hkv, s, hd)), jnp.float32)
    lens = jnp.asarray([200, 37], jnp.int32)

    f = ctx2x4.shard_map(
        functools.partial(
            distributed_flash_decode_2level, inner_axis="tp",
            outer_axis="dp", chunk_k=32, method=method, ctx=ctx2x4,
        ),
        in_specs=(P(), P(None, None, ("dp", "tp"), None),
                  P(None, None, ("dp", "tp"), None), P()),
        out_specs=P(),
    )
    out = f(q, kc, vc, lens)
    ref = gqa_decode_reference(q, kc, vc, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5,
                               rtol=2e-5)
