"""Mosaic lowering proof for every Pallas kernel (VERDICT r1 #2).

Every comm/overlap/attention kernel — and the megakernel — must LOWER
for the TPU platform, not just run in interpret mode. ``jax.export``
with ``platforms=["tpu"]`` drives the real Mosaic lowering rules from
the CPU host: tracing errors, unsupported Mosaic constructs at the
lowering layer, and shape/memory-space violations all surface here.
(The Mosaic→LLO compile inside libtpu still only happens on-device;
this is the strongest check available without a chip.)

Technique: patch the context's topology to claim ``platform="tpu"`` so
``ctx.pallas_interpret()`` returns False (kernels take the Mosaic path),
then export a jitted shard_map'd call with sharded ShapeDtypeStructs.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import export
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.runtime import mesh as mesh_mod


@pytest.fixture
def tpu_ctx():
    """8-device tp mesh whose topology claims TPU (forces Mosaic path)."""
    ctx = mesh_mod.initialize_distributed(tp=8)
    ctx.topology = dataclasses.replace(ctx.topology, platform="tpu")
    yield ctx
    mesh_mod.finalize_distributed()


@pytest.fixture
def tpu_ctx4():
    ctx = mesh_mod.initialize_distributed(
        tp=4, devices=jax.devices()[:4]
    )
    ctx.topology = dataclasses.replace(ctx.topology, platform="tpu")
    yield ctx
    mesh_mod.finalize_distributed()


@pytest.fixture
def tpu_ctx1():
    ctx = mesh_mod.initialize_distributed(
        tp=1, devices=jax.devices()[:1]
    )
    ctx.topology = dataclasses.replace(ctx.topology, platform="tpu")
    yield ctx
    mesh_mod.finalize_distributed()


def _lower(ctx, fn, *specs):
    """Export ``fn`` for TPU; any Mosaic lowering rejection raises."""
    exp = export.export(jax.jit(fn), platforms=["tpu"])(*specs)
    assert len(exp.mlir_module_serialized) > 0
    return exp


def _sds(ctx, shape, spec, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=ctx.sharding(*spec))


# -- collectives ----------------------------------------------------------

class TestCollectivesLower:
    @pytest.mark.parametrize(
        "method", ["pallas_ring", "pallas_bidir_ring", "pallas_full_mesh"]
    )
    def test_all_gather(self, tpu_ctx, method):
        from triton_distributed_tpu.ops.collectives.all_gather import (
            AllGatherMethod, all_gather,
        )

        f = tpu_ctx.shard_map(
            functools.partial(
                all_gather, axis="tp", method=AllGatherMethod(method),
                ctx=tpu_ctx,
            ),
            in_specs=P("tp", None),
            out_specs=P(None, None),
        )
        _lower(tpu_ctx, f, _sds(tpu_ctx, (8 * 16, 128), ("tp", None)))

    @pytest.mark.parametrize(
        "method", ["one_shot", "pallas_ring", "pallas_ring_hbm"]
    )
    def test_reduce_scatter(self, tpu_ctx, method):
        from triton_distributed_tpu.ops.collectives.reduce_scatter import (
            ReduceScatterMethod, reduce_scatter,
        )

        f = tpu_ctx.shard_map(
            functools.partial(
                reduce_scatter, axis="tp",
                method=ReduceScatterMethod(method), ctx=tpu_ctx,
            ),
            in_specs=P(None, None),
            out_specs=P("tp", None),
        )
        _lower(tpu_ctx, f, _sds(tpu_ctx, (8 * 16, 128), (None, None)))

    @pytest.mark.parametrize("method", ["one_shot", "two_shot"])
    def test_all_reduce(self, tpu_ctx, method):
        from triton_distributed_tpu.ops.collectives.all_reduce import (
            AllReduceMethod, all_reduce,
        )

        f = tpu_ctx.shard_map(
            functools.partial(
                all_reduce, axis="tp", method=AllReduceMethod(method),
                ctx=tpu_ctx,
            ),
            in_specs=P(None, None),
            out_specs=P(None, None),
        )
        _lower(tpu_ctx, f, _sds(tpu_ctx, (16, 128), (None, None)))

    def test_broadcast(self, tpu_ctx):
        from triton_distributed_tpu.ops.collectives.broadcast import (
            BroadcastMethod, broadcast,
        )

        f = tpu_ctx.shard_map(
            functools.partial(
                broadcast, axis="tp", root=0,
                method=BroadcastMethod.ONE_SHOT, ctx=tpu_ctx,
            ),
            in_specs=P(None, None),
            out_specs=P(None, None),
        )
        _lower(tpu_ctx, f, _sds(tpu_ctx, (16, 128), (None, None)))

    def test_all_to_all(self, tpu_ctx):
        from triton_distributed_tpu.ops.collectives.all_to_all import all_to_all

        f = tpu_ctx.shard_map(
            functools.partial(
                all_to_all, axis="tp", method="pallas", ctx=tpu_ctx
            ),
            in_specs=P("tp", None),
            out_specs=P("tp", None),
        )
        _lower(tpu_ctx, f, _sds(tpu_ctx, (8 * 8, 128), ("tp", None)))


# -- overlap kernels ------------------------------------------------------

class TestOverlapLower:
    def test_ag_gemm(self, tpu_ctx):
        from triton_distributed_tpu.ops.overlap import AGGemmConfig, ag_gemm

        f = tpu_ctx.shard_map(
            functools.partial(
                ag_gemm, axis="tp", config=AGGemmConfig(tile_n=128),
                ctx=tpu_ctx,
            ),
            in_specs=(P("tp", None), P(None, "tp")),
            out_specs=P(None, "tp"),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (8 * 16, 128), ("tp", None)),
            _sds(tpu_ctx, (128, 8 * 128), (None, "tp")),
        )

    def test_ag_gemm_adaptive(self, tpu_ctx):
        """Arrival-adaptive schedule (semaphore_read probe + SMEM order
        output) must trace and lower for TPU — it has no interpret
        path, so this is its only off-chip gate."""
        from triton_distributed_tpu.ops.overlap import AGGemmConfig, ag_gemm

        f = tpu_ctx.shard_map(
            functools.partial(
                ag_gemm, axis="tp",
                config=AGGemmConfig(tile_n=128, adaptive=True),
                ctx=tpu_ctx,
            ),
            in_specs=(P("tp", None), P(None, "tp")),
            out_specs=P(None, "tp"),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (8 * 16, 128), ("tp", None)),
            _sds(tpu_ctx, (128, 8 * 128), (None, "tp")),
        )

    def test_gemm_rs_bidir_fp8(self, tpu_ctx):
        """Dual-ring + fp8 wire hop lowering."""
        import jax.numpy as jnp

        from triton_distributed_tpu.ops.overlap import GemmRSConfig, gemm_rs

        f = tpu_ctx.shard_map(
            functools.partial(
                gemm_rs, axis="tp",
                config=GemmRSConfig(
                    tile_n=128, tile_m=8, bidir=True,
                    wire_dtype=jnp.float8_e4m3fn,
                ),
                ctx=tpu_ctx,
            ),
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (8 * 16, 8 * 128), (None, "tp")),
            _sds(tpu_ctx, (8 * 128, 128), ("tp", None)),
        )

    def test_gemm_rs(self, tpu_ctx):
        from triton_distributed_tpu.ops.overlap import GemmRSConfig, gemm_rs

        f = tpu_ctx.shard_map(
            functools.partial(
                gemm_rs, axis="tp", config=GemmRSConfig(tile_n=128),
                ctx=tpu_ctx,
            ),
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (8 * 16, 8 * 32), (None, "tp")),
            _sds(tpu_ctx, (8 * 32, 128), ("tp", None)),
        )

    @pytest.mark.parametrize("method", ["one_shot", "two_shot"])
    def test_gemm_ar(self, tpu_ctx, method):
        from triton_distributed_tpu.ops.overlap import (
            GemmARConfig, GemmARMethod, gemm_ar,
        )

        f = tpu_ctx.shard_map(
            functools.partial(
                gemm_ar, axis="tp", method=GemmARMethod(method),
                config=GemmARConfig(tile_n=128), ctx=tpu_ctx,
            ),
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P(None, None),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (16, 8 * 32), (None, "tp")),
            _sds(tpu_ctx, (8 * 32, 128), ("tp", None)),
        )


# -- attention ------------------------------------------------------------

class TestAttentionLower:
    def test_flash_attention(self, tpu_ctx):
        # Single-device kernel: export unsharded (1 logical device) —
        # a sharded export would ask XLA to auto-partition the Mosaic
        # custom call, which is unsupported by design.
        from triton_distributed_tpu.ops.attention import flash_attention

        def f(q, k, v):
            return flash_attention(
                q, k, v, causal=True, block_q=128, block_k=128
            )

        s = jax.ShapeDtypeStruct((1, 4, 256, 128), jnp.float32)
        _lower(tpu_ctx, f, s, s, s)

    def test_flash_decode(self, tpu_ctx):
        from triton_distributed_tpu.ops.attention import flash_decode

        def f(q, k, v, kv_len):
            return flash_decode(q, k, v, kv_len, chunk_k=128)

        kv = jax.ShapeDtypeStruct((2, 2, 512, 128), jnp.float32)
        _lower(
            tpu_ctx, f,
            jax.ShapeDtypeStruct((2, 8, 128), jnp.float32),
            kv, kv,
            jax.ShapeDtypeStruct((2,), jnp.int32),
        )

    def test_distributed_flash_decode(self, tpu_ctx):
        from triton_distributed_tpu.ops.attention import distributed_flash_decode

        f = tpu_ctx.shard_map(
            functools.partial(
                distributed_flash_decode, axis="tp", chunk_k=128
            ),
            in_specs=(
                P(), P(None, None, "tp", None), P(None, None, "tp", None), P(),
            ),
            out_specs=P(),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (2, 8, 128), ()),
            _sds(tpu_ctx, (2, 2, 8 * 128, 128), (None, None, "tp", None)),
            _sds(tpu_ctx, (2, 2, 8 * 128, 128), (None, None, "tp", None)),
            jax.ShapeDtypeStruct((2,), jnp.int32),
        )

    def test_sp_ag_attention(self, tpu_ctx4):
        from triton_distributed_tpu.ops.attention import sp_ag_attention

        f = tpu_ctx4.shard_map(
            functools.partial(
                sp_ag_attention, axis="tp", block_q=64, ctx=tpu_ctx4
            ),
            in_specs=(P(None, "tp", None),) * 3,
            out_specs=P(None, "tp", None),
        )
        _lower(
            tpu_ctx4, f,
            *[_sds(tpu_ctx4, (4, 256, 128), (None, "tp", None))] * 3,
        )

    def test_ring_attention(self, tpu_ctx4):
        from triton_distributed_tpu.ops.attention import ring_attention

        f = tpu_ctx4.shard_map(
            functools.partial(
                ring_attention, axis="tp", causal=True, block_q=64,
                block_k=64,
            ),
            in_specs=(P(None, "tp", None),) * 3,
            out_specs=P(None, "tp", None),
        )
        _lower(
            tpu_ctx4, f,
            *[_sds(tpu_ctx4, (4, 256, 128), (None, "tp", None))] * 3,
        )


# -- p2p / pp -------------------------------------------------------------

class TestP2PLower:
    def test_pp_shift(self, tpu_ctx):
        from triton_distributed_tpu.parallel import pp_shift

        f = tpu_ctx.shard_map(
            functools.partial(pp_shift, axis="tp", method="pallas"),
            in_specs=P("tp", None),
            out_specs=P("tp", None),
        )
        _lower(tpu_ctx, f, _sds(tpu_ctx, (8 * 8, 128), ("tp", None)))


# -- megakernel -----------------------------------------------------------

class TestMegakernelLower:
    def test_mega_decode_step(self, tpu_ctx4):
        from triton_distributed_tpu.megakernel import MegaQwen3
        from triton_distributed_tpu.models import AutoLLM

        model = AutoLLM.from_pretrained("tiny", ctx=tpu_ctx4)
        mega = MegaQwen3(model)
        _, step, _ = mega.build(1, 64)
        cache = jax.eval_shape(lambda: model.new_cache(1, 64))
        tok = jax.ShapeDtypeStruct((1,), jnp.int32)
        params = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            model.params,
        )
        exp = export.export(step, platforms=["tpu"])(params, tok, cache)
        assert len(exp.mlir_module_serialized) > 0

    def test_mega_tuned_config_lowers(self, tpu_ctx4):
        """The sweep-promotable config (deep staging + fused norms +
        cross-task prefetch) must lower for TPU — the trace-level gate
        for the MEGA_TUNED.json path (Mosaic itself only runs on chip;
        see module docstring)."""
        from triton_distributed_tpu.megakernel import MegaQwen3
        from triton_distributed_tpu.megakernel.code_generator import (
            MegaConfig,
        )
        from triton_distributed_tpu.models import AutoLLM

        model = AutoLLM.from_pretrained("tiny", ctx=tpu_ctx4)
        mega = MegaQwen3(
            model,
            cfg=MegaConfig(nbuf=4, fuse_norms=True, cross_prefetch=True),
        )
        f = jax.jit(mega.build_multi(1, 64, 2))
        cache = jax.eval_shape(lambda: model.new_cache(1, 64))
        tok = jax.ShapeDtypeStruct((1,), jnp.int32)
        params = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            model.params,
        )
        exp = export.export(f, platforms=["tpu"])(params, tok, cache)
        assert len(exp.mlir_module_serialized) > 0

    def test_mega_serving_fast_path_lowers(self, tpu_ctx4):
        """The PR 7 serving-config pieces must lower for TPU: int8
        paged pool (per-page scale operands + in-register dequant in
        the attention task) and the split AR_SEND/AR_WAIT overlapped
        collectives with their REAL barrier/semaphore machinery — the
        interpret path skips barriers (kctx.interpret), so only a
        TPU-targeted trace walks them. Single-step build: the
        multi-step (in-kernel argmax) lowering is blocked at seed by
        this jax's Mosaic integer-reduction gap (see the xfailing
        multi tests above), and every piece NEW in PR 7 except the
        argmax rides the single-step program too."""
        from triton_distributed_tpu.megakernel import MegaQwen3
        from triton_distributed_tpu.megakernel.code_generator import (
            MegaConfig,
        )
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.paged_kv_cache import (
            PagedKVCache,
        )

        model = AutoLLM.from_pretrained("tiny", ctx=tpu_ctx4)
        mega = MegaQwen3(model, cfg=MegaConfig(
            fuse_norms=True, cross_prefetch=True, overlap_ar=True
        ))
        B, page, pps, P_ = 2, 16, 4, 9
        _, f, _ = mega.build(
            B, page * pps, page, kv_quant=True, num_pages=P_,
        )
        cfg = model.cfg
        shape = (cfg.num_layers, P_, cfg.num_kv_heads, page,
                 cfg.head_dim)
        pool_sh = tpu_ctx4.sharding(None, None, "tp", None, None)
        sc_sh = tpu_ctx4.sharding(None, None, "tp")
        rep = tpu_ctx4.sharding()
        cache = PagedKVCache(
            k_pages=jax.ShapeDtypeStruct(shape, jnp.int8,
                                         sharding=pool_sh),
            v_pages=jax.ShapeDtypeStruct(shape, jnp.int8,
                                         sharding=pool_sh),
            page_table=jax.ShapeDtypeStruct((B, pps), jnp.int32,
                                            sharding=rep),
            kv_len=jax.ShapeDtypeStruct((B,), jnp.int32, sharding=rep),
            k_scale=jax.ShapeDtypeStruct(
                (cfg.num_layers, P_, cfg.num_kv_heads), jnp.float32,
                sharding=sc_sh,
            ),
            v_scale=jax.ShapeDtypeStruct(
                (cfg.num_layers, P_, cfg.num_kv_heads), jnp.float32,
                sharding=sc_sh,
            ),
        )
        tok = jax.ShapeDtypeStruct((B,), jnp.int32, sharding=rep)
        params = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=x.sharding
            ),
            model.params,
        )
        exp = export.export(f, platforms=["tpu"])(params, tok, cache)
        assert len(exp.mlir_module_serialized) > 0

    def test_mega_wq8_lowers(self, tpu_ctx4):
        """Weight-only int8 decode must lower for TPU (int8 staging
        tiles, VMEM scale operands, upcast-at-MXU dots)."""
        from triton_distributed_tpu.megakernel import MegaQwen3
        from triton_distributed_tpu.megakernel.code_generator import (
            MegaConfig,
        )
        from triton_distributed_tpu.models import AutoLLM

        model = AutoLLM.from_pretrained("tiny", ctx=tpu_ctx4)
        mega = MegaQwen3(model, cfg=MegaConfig(wq8=True))
        qp = mega.quantized_params()
        f = jax.jit(mega.build_multi(1, 64, 2))
        cache = jax.eval_shape(lambda: model.new_cache(1, 64))
        tok = jax.ShapeDtypeStruct((1,), jnp.int32)
        qspec = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            qp,
        )
        exp = export.export(f, platforms=["tpu"])(qspec, tok, cache)
        assert len(exp.mlir_module_serialized) > 0


class TestBaselineShapesLower:
    """The survey north-star shapes (M=8192, K=4096, N=12288, tp=8,
    bf16 — VERDICT r1 #3/#5) must lower for TPU: tiled staging keeps
    VMEM bounded no matter how big m_per × K grows."""

    def test_ag_gemm_baseline_shape(self, tpu_ctx):
        from triton_distributed_tpu.ops.overlap import ag_gemm
        from triton_distributed_tpu.ops.overlap.ag_gemm import (
            create_ag_gemm_context,
        )

        M, K, N = 8192, 4096, 12288
        cfg = create_ag_gemm_context(M // 8, N // 8, K, jnp.bfloat16)
        # Staging stays VMEM-bounded regardless of shard size (the
        # sweep-tuned budget caps the A double buffer, not the shard).
        from triton_distributed_tpu.ops.overlap.ag_gemm import _AG_STAGE_BUDGET

        assert cfg.tile_m * K * 2 <= _AG_STAGE_BUDGET
        big = create_ag_gemm_context(1 << 20, N // 8, K, jnp.bfloat16)
        assert big.tile_m * K * 2 <= _AG_STAGE_BUDGET
        from triton_distributed_tpu.ops.overlap import AGGemmConfig

        # Lower both the tuned config and an explicitly chunked one
        # (tile_m < m_per → num_i > 1) so the multi-M-tile staging path
        # keeps TPU-lowering coverage now that the tuned default stages
        # the whole 1024-row shard in one tile.
        for c in (cfg, AGGemmConfig(tile_n=512, tile_m=256)):
            f = tpu_ctx.shard_map(
                functools.partial(ag_gemm, axis="tp", config=c, ctx=tpu_ctx),
                in_specs=(P("tp", None), P(None, "tp")),
                out_specs=P(None, "tp"),
            )
            _lower(
                tpu_ctx, f,
                _sds(tpu_ctx, (M, K), ("tp", None), jnp.bfloat16),
                _sds(tpu_ctx, (K, N), (None, "tp"), jnp.bfloat16),
            )

    def test_gemm_rs_baseline_shape(self, tpu_ctx):
        from triton_distributed_tpu.ops.overlap import gemm_rs
        from triton_distributed_tpu.ops.overlap.gemm_rs import (
            create_gemm_rs_context,
        )

        M, K, N = 8192, 12288, 4096  # down-proj: k_loc = K/8
        cfg = create_gemm_rs_context(M, N, K // 8, jnp.bfloat16, n_ranks=8)
        f = tpu_ctx.shard_map(
            functools.partial(gemm_rs, axis="tp", config=cfg, ctx=tpu_ctx),
            in_specs=(P(None, "tp"), P("tp", None)),
            out_specs=P("tp", None),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (M, K), (None, "tp"), jnp.bfloat16),
            _sds(tpu_ctx, (K, N), ("tp", None), jnp.bfloat16),
        )


class TestLowLatencyLower:
    def test_ll_all_gather_barrier_free(self, tpu_ctx):
        """The TPU (barrier-free, ack-semaphore) variant must lower."""
        from triton_distributed_tpu.ops import (
            ll_all_gather, ll_all_gather_workspace,
        )

        def body(x, ws, phase):
            return ll_all_gather(
                x, ws, phase, axis="tp", ctx=tpu_ctx, barrier_free=True
            )

        f = tpu_ctx.shard_map(
            body,
            in_specs=(P("tp", None), P(), P()),
            out_specs=(P(None, None), P()),
        )
        ws = jax.eval_shape(
            lambda: ll_all_gather_workspace(8, 16, 128, jnp.float32)
        )
        ws = jax.ShapeDtypeStruct(ws.shape, ws.dtype, sharding=tpu_ctx.sharding())
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (8 * 16, 128), ("tp", None)),
            ws,
            jax.ShapeDtypeStruct((), jnp.int32, sharding=tpu_ctx.sharding()),
        )

    @pytest.mark.parametrize("nranks", [1, 4])
    @pytest.mark.parametrize("sampled", [False, True])
    def test_mega_multi_step_decode(self, request, nranks, sampled):
        """The multi-step kernel (2-D grid, SMEM token feedback, band
        attention, in-kernel argmax) must lower for TPU — including the
        tp>1 cross-rank argmax exchange and the Gumbel-noise input."""
        from triton_distributed_tpu.megakernel import MegaQwen3
        from triton_distributed_tpu.models import AutoLLM

        ctx = request.getfixturevalue(f"tpu_ctx{nranks}")
        model = AutoLLM.from_pretrained("tiny", ctx=ctx)
        mega = MegaQwen3(model)
        f = jax.jit(mega.build_multi(1, 64, 4, sampled=sampled))
        cache = jax.eval_shape(lambda: model.new_cache(1, 64))
        tok = jax.ShapeDtypeStruct((1,), jnp.int32)
        params = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            model.params,
        )
        args = [params, tok, cache]
        if sampled:
            v_pad = model.params.lm_head.shape[1]
            args.append(
                jax.ShapeDtypeStruct((4, 1, v_pad), jnp.float32)
            )
        exp = export.export(f, platforms=["tpu"])(*args)
        assert len(exp.mlir_module_serialized) > 0


class TestBidirRSLower:
    def test_reduce_scatter_bidir(self, tpu_ctx):
        import functools

        from triton_distributed_tpu.ops.collectives.reduce_scatter import (
            ReduceScatterMethod,
            reduce_scatter,
        )

        f = tpu_ctx.shard_map(
            functools.partial(
                reduce_scatter, axis="tp",
                method=ReduceScatterMethod.PALLAS_BIDIR_RING, ctx=tpu_ctx,
            ),
            in_specs=P(None, None),
            out_specs=P("tp", None),
        )
        _lower(tpu_ctx, f, _sds(tpu_ctx, (8 * 8, 128), (None, None)))


class TestEPExchangeLower:
    def test_ep_exchange(self, tpu_ctx):
        """The device-initiated EP transport is the AUTO default on real
        TPU — its Mosaic lowering (dynamic-trip fori_loop waits,
        put_signal under pl.when, SMEM scalar bounds) needs an off-chip
        gate like every other TPU-only kernel."""
        import functools

        import jax.numpy as jnp

        from triton_distributed_tpu.ops.moe.ep_exchange import ep_exchange

        n = 8

        def body(rows, splits, counts):
            return ep_exchange(rows, splits, counts, axis="tp", ctx=tpu_ctx)

        f = tpu_ctx.shard_map(
            functools.partial(body),
            in_specs=(P(None, None, None), P(None), P(None)),
            out_specs=P(None, None, None),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (n, 64, 256), (None, None, None), jnp.uint8),
            _sds(tpu_ctx, (n,), (None,), jnp.int32),
            _sds(tpu_ctx, (n,), (None,), jnp.int32),
        )

    def test_ep_moe_ffn_pallas(self, tpu_ctx):
        """Whole EP MoE layer with the device transport lowers."""
        import functools

        from triton_distributed_tpu.ops.moe import ep_moe_ffn

        f = tpu_ctx.shard_map(
            functools.partial(
                ep_moe_ffn, k=2, axis="tp", method="pallas", ctx=tpu_ctx
            ),
            in_specs=(P("tp", None), P(), P("tp", None, None),
                      P("tp", None, None)),
            out_specs=P("tp", None),
        )
        _lower(
            tpu_ctx, f,
            _sds(tpu_ctx, (8 * 8, 128), ("tp", None)),
            _sds(tpu_ctx, (128, 16), (None, None)),
            _sds(tpu_ctx, (16, 128, 2 * 128), ("tp", None, None)),
            _sds(tpu_ctx, (16, 128, 128), ("tp", None, None)),
        )


class TestHeadlineGeometryLower:
    """The round-4 headline-class ladders (VERDICT r3 task 4) run
    Qwen3-1.7B / Qwen3-4B geometry on the chip; their per-layer dims
    (d=2048/2560, o_k=4096, f=6144/9728) must lower BEFORE a relay
    window is spent on them. Layers/vocab are reduced — they change
    tile counts, not tile shapes (full-vocab lm streams are
    chip-proven at 0.6B)."""

    @pytest.mark.parametrize("preset", ["Qwen/Qwen3-1.7B", "Qwen/Qwen3-4B"])
    def test_mega_multi_lowers(self, tpu_ctx1, preset):
        from triton_distributed_tpu.megakernel import MegaQwen3
        from triton_distributed_tpu.models import AutoLLM

        model = AutoLLM.from_pretrained(
            preset, ctx=tpu_ctx1, max_length=128,
            num_layers=2, vocab_size=32768,
        )
        mega = MegaQwen3(model)
        f = jax.jit(mega.build_multi(1, 128, 4))
        cache = jax.eval_shape(lambda: model.new_cache(1, 128))
        tok = jax.ShapeDtypeStruct((1,), jnp.int32)
        params = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=x.sharding
            ),
            model.params,
        )
        exp = export.export(f, platforms=["tpu"])(params, tok, cache)
        assert len(exp.mlir_module_serialized) > 0

    def test_mega_q8_synth_8b_geometry_lowers(self, tpu_ctx1):
        """The beyond-HBM path (perf/ladder_q8_synth.py): 8B-geometry
        wq8 decode from synthesized Q8Params, no bf16 tree."""
        from triton_distributed_tpu.megakernel import MegaQwen3
        from triton_distributed_tpu.megakernel.code_generator import (
            MegaConfig,
        )
        from triton_distributed_tpu.models.config import get_config
        from triton_distributed_tpu.models.qwen import Qwen3

        cfg = get_config(
            "Qwen/Qwen3-8B", max_length=128,
            num_layers=2, vocab_size=32768,
        )
        model = Qwen3(cfg, ctx=tpu_ctx1)  # params stay None
        mega = MegaQwen3(model, cfg=MegaConfig(wq8=True))
        qp = mega.quantized_init(jax.random.PRNGKey(0))
        f = jax.jit(mega.build_multi(1, 128, 4))
        cache = jax.eval_shape(lambda: model.new_cache(1, 128))
        tok = jax.ShapeDtypeStruct((1,), jnp.int32)
        qshapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=x.sharding
            ),
            qp,
        )
        exp = export.export(f, platforms=["tpu"])(qshapes, tok, cache)
        assert len(exp.mlir_module_serialized) > 0
