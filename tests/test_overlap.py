"""AG+GEMM and GEMM+RS overlap-kernel correctness.

Parity: reference ``test/nvidia/test_ag_gemm.py`` / ``test_gemm_rs.py``
(golden = NCCL allgather + torch.matmul; here numpy).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops.overlap import (
    AGGemmConfig,
    GemmARConfig,
    GemmARMethod,
    GemmRSConfig,
    ag_gemm_op,
    gemm_ar_op,
    gemm_rs_op,
)


@pytest.mark.parametrize("tile_n", [128, 256])
def test_ag_gemm(ctx4, rng, tile_n):
    M, K, N = 4 * 32, 128, 1024
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=tile_n), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_ag_gemm_8dev(ctx8, rng):
    # Keep per-device buffers <=64KB: the 1-core CI host deadlocks XLA's
    # CPU client when 8 interpret-mode devices move large buffers at once.
    M, K, N = 8 * 16, 128, 128
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=128), ctx8)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_ag_gemm_chunked_staging(ctx4, rng):
    # tile_m < m_per forces the multi-M-tile staging path (_land_current
    # / _prefetch_same_chunk buffer parity) that the sweep-tuned default
    # configs skip at small shapes.
    M, K, N = 4 * 32, 128, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=128, tile_m=8), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_gemm_rs_chunked_staging(ctx4, rng):
    M, K, N = 4 * 32, 256, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_rs_op(a, b, "tp", GemmRSConfig(tile_n=128, tile_m=8), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("tile_n", [128, 256])
def test_gemm_rs(ctx4, rng, tile_n):
    M, K, N = 4 * 32, 256, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_rs_op(a, b, "tp", GemmRSConfig(tile_n=tile_n), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_gemm_rs_8dev(ctx8, rng):
    M, K, N = 8 * 8, 256, 128
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_rs_op(a, b, "tp", GemmRSConfig(tile_n=128), ctx8)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("method", [GemmARMethod.ONE_SHOT, GemmARMethod.TWO_SHOT])
def test_gemm_ar(ctx4, rng, method):
    M, K, N = 4 * 8, 256, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_ar_op(a, b, "tp", method, GemmARConfig(tile_n=128), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_gemm_ar_one_shot_8dev(ctx8, rng):
    M, K, N = 16, 256, 128
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_ar_op(a, b, "tp", GemmARMethod.ONE_SHOT, GemmARConfig(tile_n=128), ctx8)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_ag_gemm_bf16(ctx4, rng):
    M, K, N = 4 * 32, 128, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32)).astype(jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32)).astype(jnp.bfloat16)
    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=128), ctx4)
    gold = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    np.testing.assert_allclose(np.asarray(out, np.float32), gold, rtol=5e-2, atol=5e-1)
