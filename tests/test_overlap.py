"""AG+GEMM and GEMM+RS overlap-kernel correctness.

Parity: reference ``test/nvidia/test_ag_gemm.py`` / ``test_gemm_rs.py``
(golden = NCCL allgather + torch.matmul; here numpy).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops.overlap import (
    AGGemmConfig,
    GemmARConfig,
    GemmARMethod,
    GemmRSConfig,
    ag_gemm_op,
    gemm_ar_op,
    gemm_rs_op,
)


@pytest.mark.parametrize("tile_n", [128, 256])
def test_ag_gemm(ctx4, rng, tile_n):
    M, K, N = 4 * 32, 128, 1024
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=tile_n), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_ag_gemm_8dev(ctx8, rng):
    # Keep per-device buffers <=64KB: the 1-core CI host deadlocks XLA's
    # CPU client when 8 interpret-mode devices move large buffers at once.
    M, K, N = 8 * 16, 128, 128
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=128), ctx8)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_ag_gemm_chunked_staging(ctx4, rng):
    # tile_m < m_per forces the multi-M-tile staging path (_land_current
    # / _prefetch_same_chunk buffer parity) that the sweep-tuned default
    # configs skip at small shapes.
    M, K, N = 4 * 32, 128, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=128, tile_m=8), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_gemm_rs_chunked_staging(ctx4, rng):
    M, K, N = 4 * 32, 256, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_rs_op(a, b, "tp", GemmRSConfig(tile_n=128, tile_m=8), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("tile_n", [128, 256])
def test_gemm_rs(ctx4, rng, tile_n):
    M, K, N = 4 * 32, 256, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_rs_op(a, b, "tp", GemmRSConfig(tile_n=tile_n), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_gemm_rs_8dev(ctx8, rng):
    M, K, N = 8 * 8, 256, 128
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_rs_op(a, b, "tp", GemmRSConfig(tile_n=128), ctx8)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("bidir", [False, True])
def test_gemm_rs_bidir(ctx4, rng, bidir):
    """Counter-rotating dual rings (both ICI directions) vs the single
    ring and the XLA golden — same reduction, different wire routes."""
    M, K, N = 4 * 32, 256, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    cfg = GemmRSConfig(tile_n=128, tile_m=8, bidir=bidir)
    out = gemm_rs_op(a, b, "tp", cfg, ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_gemm_rs_bidir_8dev(ctx8, rng):
    M, K, N = 8 * 16, 256, 128
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    cfg = GemmRSConfig(tile_n=128, tile_m=8, bidir=True)
    out = gemm_rs_op(a, b, "tp", cfg, ctx8)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_gemm_rs_fp8_wire(ctx4, rng):
    """fp8 ring-hop payload: error bounded by the documented model
    (~sqrt(hops)·2^-4 relative on partial magnitudes). Inputs scaled
    well inside e4m3 range; golden = f64 matmul."""
    M, K, N = 4 * 32, 256, 256
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.1, jnp.float32)
    b = jnp.asarray(rng.standard_normal((K, N)) * 0.1, jnp.float32)
    cfg = GemmRSConfig(tile_n=128, tile_m=8, wire_dtype=jnp.float8_e4m3fn)
    out = gemm_rs_op(a, b, "tp", cfg, ctx4)
    gold = np.asarray(a, np.float64) @ np.asarray(b, np.float64)
    assert not np.isnan(np.asarray(out)).any()
    # Error model: ~2^-4 relative PER HOP on the PARTIAL magnitudes
    # (sqrt(3) hops at n=4); where the final sum cancels, relative-to-
    # final error is unbounded by design — bound the median relative
    # error and the worst ABSOLUTE error against the partial scale
    # (rows of a@b partials here are ~0.15 in magnitude).
    err = np.abs(np.asarray(out, np.float64) - gold)
    rel = err / (np.abs(gold) + 1e-3)
    assert np.median(rel) < 0.08, float(np.median(rel))
    assert np.max(err) < 0.06, float(np.max(err))


@pytest.mark.parametrize("method", [GemmARMethod.ONE_SHOT, GemmARMethod.TWO_SHOT])
def test_gemm_ar(ctx4, rng, method):
    M, K, N = 4 * 8, 256, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_ar_op(a, b, "tp", method, GemmARConfig(tile_n=128), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_gemm_ar_one_shot_8dev(ctx8, rng):
    M, K, N = 16, 256, 128
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_ar_op(a, b, "tp", GemmARMethod.ONE_SHOT, GemmARConfig(tile_n=128), ctx8)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )


def test_ag_gemm_bf16(ctx4, rng):
    M, K, N = 4 * 32, 128, 256
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32)).astype(jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32)).astype(jnp.bfloat16)
    out = ag_gemm_op(a, b, "tp", AGGemmConfig(tile_n=128), ctx4)
    gold = np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    np.testing.assert_allclose(np.asarray(out, np.float32), gold, rtol=5e-2, atol=5e-1)


def test_gemm_rs_force_kernel_n1(rng):
    """force_kernel must run the real staging pipeline at n=1 (the
    sweep's rung) and match the dot it normally short-circuits to."""
    import jax

    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx1 = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    try:
        a = jnp.asarray(rng.standard_normal((32, 128)), jnp.float32)
        b = jnp.asarray(rng.standard_normal((128, 256)), jnp.float32)
        cfg = GemmRSConfig(tile_n=128, tile_m=8, force_kernel=True)
        out = gemm_rs_op(a, b, "tp", cfg, ctx1)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(a) @ np.asarray(b),
            rtol=1e-4, atol=1e-4,
        )
    finally:
        mesh_mod.finalize_distributed()
