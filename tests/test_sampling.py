"""Direct coverage for ``models/sampling.py`` — previously exercised
only indirectly through the engine tests. The filtered-support
semantics matter doubly now: the speculative verifier scores drafts
against ``target_probs``, which must be EXACTLY the distribution
``sample`` draws from.
"""

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.models import sampling


def _logits(vals):
    return jnp.asarray(np.asarray(vals, np.float32))


def test_greedy_and_nonpositive_temperature():
    logits = _logits([[0.1, 2.0, -1.0, 0.5], [3.0, 0.0, 1.0, 2.9]])
    np.testing.assert_array_equal(np.asarray(sampling.greedy(logits)), [1, 0])
    key = jax.random.key(0)
    for t in (0.0, -1.0):
        np.testing.assert_array_equal(
            np.asarray(sampling.sample(logits, key, temperature=t)), [1, 0]
        )


def test_fixed_key_determinism():
    logits = _logits(np.linspace(-1, 1, 16))
    key = jax.random.key(7)
    a = int(sampling.sample(logits, key, temperature=0.9, top_p=0.8, top_k=5))
    b = int(sampling.sample(logits, key, temperature=0.9, top_p=0.8, top_k=5))
    assert a == b
    # A different key must be able to move the draw (flat-ish logits).
    draws = {
        int(sampling.sample(logits, jax.random.key(s), temperature=2.0))
        for s in range(32)
    }
    assert len(draws) > 1


def test_top_p_keeps_top_token():
    # One dominant token: even a tiny top_p keeps it (the filter always
    # retains the argmax), and the sample can only be that token.
    logits = _logits([10.0, 0.0, -1.0, -2.0])
    filtered = np.asarray(
        sampling.filter_logits(logits, temperature=1.0, top_p=0.01)
    )
    assert np.isfinite(filtered[0])
    assert np.all(np.isneginf(filtered[1:]))
    for s in range(8):
        assert int(sampling.sample(logits, jax.random.key(s), 1.0, 0.01)) == 0


def test_top_p_cutoff_is_smallest_covering_prefix():
    probs = np.asarray([0.5, 0.3, 0.15, 0.05], np.float64)
    logits = _logits(np.log(probs))
    # top_p=0.75 needs {0.5, 0.3} (0.5 alone < 0.75).
    filtered = np.asarray(sampling.filter_logits(logits, 1.0, top_p=0.75))
    assert np.isfinite(filtered[:2]).all() and np.isneginf(filtered[2:]).all()


def test_top_k_masks_support():
    logits = _logits([4.0, 3.0, 2.0, 1.0, 0.0])
    filtered = np.asarray(sampling.filter_logits(logits, 1.0, top_k=2))
    assert np.isfinite(filtered[:2]).all() and np.isneginf(filtered[2:]).all()
    # top_k=0 disables; top_k >= V is a no-op.
    for k in (0, 5, 9):
        f = np.asarray(sampling.filter_logits(logits, 1.0, top_k=k))
        assert np.isfinite(f).all()
    draws = {
        int(sampling.sample(logits, jax.random.key(s), 2.0, top_k=3))
        for s in range(64)
    }
    assert draws <= {0, 1, 2} and len(draws) > 1


def test_target_probs_matches_sample_distribution():
    """``target_probs`` must be the distribution ``sample`` draws from
    (the speculative acceptance rule depends on it): empirical sample
    frequencies converge to it."""
    rng = np.random.default_rng(3)
    logits = _logits(rng.normal(size=8) * 2.0)
    t, p, k = 0.8, 0.9, 5
    probs = np.asarray(sampling.target_probs(logits, t, p, k), np.float64)
    assert abs(probs.sum() - 1.0) < 1e-5
    n = 4000
    keys = jax.random.split(jax.random.key(11), n)
    batched = jax.vmap(lambda kk: sampling.sample(logits, kk, t, p, k))
    draws = np.asarray(batched(keys))
    emp = np.bincount(draws, minlength=8) / n
    # Support agrees exactly; frequencies within statistical noise.
    assert set(np.nonzero(emp)[0]) <= set(np.nonzero(probs > 0)[0])
    assert np.abs(emp - probs).sum() / 2 < 0.05  # total variation


def test_target_probs_greedy_is_one_hot():
    logits = _logits([0.0, 5.0, 1.0])
    probs = np.asarray(sampling.target_probs(logits, temperature=0.0))
    np.testing.assert_allclose(probs, [0.0, 1.0, 0.0])


def test_gumbel_max_matches_target_distribution():
    """The megakernel's in-kernel sampling IS ``argmax(logits + T·g)``
    with standard-Gumbel ``g`` (the serving wrapper draws the noise,
    the kernel argmaxes) — by the Gumbel-max trick this must draw
    exactly the ``sampling.sample`` / ``target_probs`` distribution at
    ``top_p=1, top_k=0``, the shared filtered-distribution definition
    the mega fast path relies on (filtered slots fall back to host
    sampling)."""
    rng = np.random.default_rng(5)
    logits = _logits(rng.normal(size=8) * 2.0)
    t = 0.7
    probs = np.asarray(sampling.target_probs(logits, t), np.float64)
    n = 4000
    keys = jax.random.split(jax.random.key(13), n)

    def draw(kk):
        noise = t * jax.random.gumbel(kk, (8,), jnp.float32)
        return jnp.argmax(logits + noise)

    draws = np.asarray(jax.vmap(draw)(keys))
    emp = np.bincount(draws, minlength=8) / n
    assert np.abs(emp - probs).sum() / 2 < 0.05  # total variation
    # Per-slot temperature 0 degenerates to the greedy argmax.
    zero = jnp.argmax(logits + 0.0 * jax.random.gumbel(
        jax.random.key(1), (8,), jnp.float32
    ))
    assert int(zero) == int(sampling.greedy(logits))


# -- filter edge cases (ISSUE 16: the tree verifier samples through
# -- these exact filters at every node) ------------------------------------


def test_top_k_at_or_above_vocab_matches_disabled():
    """``top_k >= V`` must be EXACTLY the disabled filter (not an
    off-by-one that drops the minimum): same filtered logits, same
    target distribution, for k = V and beyond."""
    rng = np.random.default_rng(9)
    logits = _logits(rng.normal(size=6) * 3.0)
    base = np.asarray(sampling.filter_logits(logits, 0.7, top_k=0))
    for k in (6, 7, 100):
        np.testing.assert_array_equal(
            np.asarray(sampling.filter_logits(logits, 0.7, top_k=k)), base
        )
        np.testing.assert_array_equal(
            np.asarray(sampling.target_probs(logits, 0.7, top_k=k)),
            np.asarray(sampling.target_probs(logits, 0.7, top_k=0)),
        )
    assert np.isfinite(base).all()  # nothing masked


def test_top_p_one_is_pure_temperature_scaling():
    """``top_p=1.0`` takes the no-filter branch exactly: full support,
    and ``target_probs`` is the plain tempered softmax."""
    rng = np.random.default_rng(10)
    logits = _logits(rng.normal(size=8))
    t = 0.6
    filtered = np.asarray(sampling.filter_logits(logits, t, top_p=1.0))
    np.testing.assert_array_equal(
        filtered, np.asarray(logits, np.float32) / t
    )
    probs = np.asarray(sampling.target_probs(logits, t, top_p=1.0))
    expect = np.asarray(jax.nn.softmax(jnp.asarray(filtered)))
    np.testing.assert_allclose(probs, expect, rtol=1e-6)
    assert (probs > 0).all()


def test_top_p_epsilon_boundary_around_cutoff():
    """The nucleus keeps the smallest prefix whose cumulative mass
    REACHES top_p: a hair below the top token's own mass keeps just it,
    a hair above pulls in exactly one more token — the boundary the
    acceptance rule's support comparison sits on."""
    probs = np.asarray([0.5, 0.3, 0.15, 0.05], np.float64)
    logits = _logits(np.log(probs))
    eps = 1e-3
    lo = np.asarray(sampling.filter_logits(logits, 1.0, top_p=0.5 - eps))
    assert np.isfinite(lo[0]) and np.isneginf(lo[1:]).all()
    hi = np.asarray(sampling.filter_logits(logits, 1.0, top_p=0.5 + eps))
    assert np.isfinite(hi[:2]).all() and np.isneginf(hi[2:]).all()
    # And the renormalized target matches the surviving prefix exactly.
    tp = np.asarray(sampling.target_probs(logits, 1.0, top_p=0.5 + eps))
    np.testing.assert_allclose(tp[:2], [0.5 / 0.8, 0.3 / 0.8], rtol=1e-5)
    np.testing.assert_allclose(tp[2:], 0.0)


def test_temperature_limit_converges_to_greedy():
    """``t → 0+`` converges on the ``t=0`` one-hot path: the sampled
    token equals the argmax for every key and the target distribution
    approaches one-hot — no cliff between the two code paths."""
    logits = _logits([0.3, 2.1, -0.5, 1.9, 0.0])
    best = int(sampling.greedy(logits))
    for t in (1e-2, 1e-4):
        for s in range(6):
            assert int(
                sampling.sample(logits, jax.random.key(s), t)
            ) == best
        probs = np.asarray(sampling.target_probs(logits, t))
        assert probs[best] > 1.0 - 1e-6
    np.testing.assert_allclose(
        np.asarray(sampling.target_probs(logits, 0.0)),
        np.eye(5, dtype=np.float32)[best],
    )
