"""SLO goodput yardstick tests (docs/observability.md "SLO goodput",
docs/serving.md "Streaming & cancellation").

Layers of evidence:

- the streaming WIRE GRAMMAR on a stub server: per-request frame
  indices strictly increasing from 0, monotone wire stamps, a summary
  whose outputs equal the streamed tokens, the pure reference
  generator, AND a non-streaming request for the same payload —
  streaming changes transport, never tokens;
- client-driven cancellation: mid-stream via the cancel verb frees
  the slot's pages (audit clean, pool partition whole) and returns
  the partial tokens with status ``cancelled``; the same verb aborts
  queued and in-flight requests through a REAL ``ContinuousEngine``
  (tiny model) with ``tdt_requests_total{status="cancelled"}`` and a
  ``cancel`` event; the cancel-vs-natural-finish race is sequenced
  deterministically through the ``engine.cancel`` seam;
- chaos: an injected ``stream.send`` drop mid-stream reads as a
  client disconnect — the payload's requests cancel, the engine
  survives bit-exact for the next connection, audits clean;
- loadgen determinism: same seed → same trace, save/load round-trip,
  Zipf head concentration, bursty arrival clumping;
- SLO math: spec evaluation, outcome counting, goodput, the
  missing-duration-on-failure rule, cancelled-excluded denominator;
- exposition merge: replica labels injected (escaping included),
  HELP/TYPE once, values preserved — the pure half of the fleet
  scrape; and (where child processes spawn) the ISSUE-13 acceptance:
  one ``{"cmd": "metrics", "scope": "fleet"}`` scrape against a live
  stub fleet whose per-replica series equal the children's own
  scrapes, plus a replica-tagged ``fleet_seq``-stitched event stream.
"""

import re
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from triton_distributed_tpu.models.stub import StubEngine, stub_generate
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs import slo as obs_slo
from triton_distributed_tpu.obs.timeline import Timeline
from triton_distributed_tpu.runtime.faults import FaultPlan
from triton_distributed_tpu.serving.server import (
    ModelServer,
    request,
    request_stream,
)


def _can_spawn() -> bool:
    try:
        return subprocess.run(
            [sys.executable, "-c", "pass"], timeout=60
        ).returncode == 0
    except Exception:  # noqa: BLE001 — any failure means "cannot"
        return False


_SPAWN_OK = _can_spawn()
needs_procs = pytest.mark.skipif(
    not _SPAWN_OK or not hasattr(signal, "SIGKILL"),
    reason="child-process spawning unavailable on this platform",
)

PROMPT = list(range(1, 9))


def _stub_server(**kw):
    eng = StubEngine(num_pages=64, page_size=4,
                     delay_s=kw.pop("delay_s", 0.0))
    server = ModelServer(eng, **kw).start()
    return eng, server


def _pool_whole(eng: StubEngine) -> bool:
    return (len(eng.pool.free) + eng.prefix.node_count
            == eng.pool.num_pages)


# -- streaming wire grammar ------------------------------------------------


def test_stream_wire_grammar_and_token_identity():
    eng, server = _stub_server()
    try:
        payload = {"requests": [PROMPT, list(range(40, 46))],
                   "gen_lens": [6, 4], "ticket_ids": ["a", "b"]}
        frames = list(request_stream(server.host, server.port, payload))
        summary = frames[-1]
        tokens = frames[:-1]
        assert summary["frame"] == "summary"
        assert all(f["frame"] == "token" for f in tokens)
        # Per-request indices strictly increasing from 0; stamps
        # monotone in arrival order (one wire, one clock).
        per_tid: dict = {}
        last_t = 0.0
        for f in tokens:
            assert f["t"] >= last_t
            last_t = f["t"]
            assert f["i"] == len(per_tid.setdefault(f["tid"], []))
            per_tid[f["tid"]].append(f["token"])
        golds = [stub_generate(PROMPT, 6),
                 stub_generate(list(range(40, 46)), 4)]
        assert per_tid["a"] == golds[0] == summary["outputs"][0]
        assert per_tid["b"] == golds[1] == summary["outputs"][1]
        assert summary["ticket_ids"] == ["a", "b"]
        # Wire-side latency entries: TTFT always, TPOT with >= 2 tokens.
        for w in summary["wire"]:
            assert w["ttft_s"] is not None and w["ttft_s"] >= 0
            assert w["tpot_s"] is not None
            assert w["outcome"] == "met"  # no deadlines configured
        # Streaming never changes tokens: the non-streaming response
        # for the same payload is identical.
        plain = request(server.host, server.port, {
            "requests": payload["requests"],
            "gen_lens": payload["gen_lens"],
        })
        assert plain["outputs"] == summary["outputs"]
        assert eng.audit() == [] and _pool_whole(eng)
    finally:
        server.shutdown()


def test_stream_assigns_ticket_ids_when_absent():
    eng, server = _stub_server()
    try:
        frames = list(request_stream(
            server.host, server.port,
            {"requests": [PROMPT], "gen_lens": [3]},
        ))
        summary = frames[-1]
        tids = summary["ticket_ids"]
        assert len(tids) == 1 and isinstance(tids[0], str) and tids[0]
        assert all(f["tid"] == tids[0] for f in frames[:-1])
    finally:
        server.shutdown()


def test_stream_refused_on_fixed_batch_payload():
    eng, server = _stub_server()
    try:
        with pytest.raises(RuntimeError, match="bad_request"):
            list(request_stream(
                server.host, server.port,
                {"input_ids": [PROMPT], "gen_len": 4},
            ))
    finally:
        server.shutdown()


# -- cancellation ----------------------------------------------------------


def test_cancel_mid_stream_frees_pages():
    """ISSUE-13 acceptance: a mid-stream client cancellation tears
    the slot down with a clean audit and pages returned to the pool."""
    eng, server = _stub_server(delay_s=2.0)
    try:
        got: list = []
        done = threading.Event()

        def run():
            try:
                for f in request_stream(
                    server.host, server.port,
                    {"requests": [PROMPT], "gen_lens": [40],
                     "ticket_ids": ["c1"]}, timeout=60,
                ):
                    got.append(f)
            finally:
                done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len([
            f for f in got
            if isinstance(f, dict) and f.get("frame") == "token"
        ]) < 2:
            time.sleep(0.01)
        # Second connection, mid-generation: the verb is engine-lock-free.
        resp = request(server.host, server.port,
                       {"cmd": "cancel", "ticket_ids": ["c1"]})
        assert resp["ok"] and resp["requested"] == 1
        assert done.wait(30)
        summary = got[-1]
        assert summary["frame"] == "summary"
        assert summary["results"][0]["status"] == "cancelled"
        n_out = len(summary["outputs"][0])
        assert 0 < n_out < 40
        # Partial tokens are the true prefix of the full generation.
        assert summary["outputs"][0] == stub_generate(PROMPT, 40)[:n_out]
        assert summary["wire"][0]["outcome"] == "cancelled"
        assert eng.last_stats["cancelled_requests"] == 1
        assert eng.audit() == [] and _pool_whole(eng)
    finally:
        server.shutdown()


def test_cancel_through_continuous_engine(fresh_telemetry):
    """The non-streaming satellite: the cancel set aborts queued AND
    in-flight requests through a REAL ContinuousEngine — today
    ``aborted`` only fired on loop teardown. Deterministic: the
    in-flight cancel is issued from the victim's own on_token callback
    (applied at the next scheduling round), the queued cancel is
    pre-armed before run()."""
    import jax

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import (
        ContinuousEngine,
        Request,
    )
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(
        tp=4, devices=jax.devices()[:4]
    )
    try:
        model = AutoLLM.from_pretrained("tiny", ctx=ctx)
        eng = ContinuousEngine(model, max_batch=2, page_size=16,
                               max_length=64, prefix_cache=True)
        prompts = [np.arange(1, 9, dtype=np.int32),
                   np.arange(20, 28, dtype=np.int32),
                   np.arange(30, 38, dtype=np.int32)]
        # Golden for the surviving request, solo.
        [gold] = eng.run([Request(prompts[2], 6)], results=True)
        assert gold.status == "ok" and len(gold.tokens) == 6

        victim = Request(prompts[0], 8, ticket_id="vic")
        victim.on_token = (
            lambda i, tok: eng.cancel(["vic", "queued"]) if i == 1
            else None
        )
        survivor = Request(prompts[2], 6, ticket_id="srv")
        # max_batch=2: the third request queues; its id is cancelled
        # mid-flight by the victim's callback above. The engine.cancel
        # seam sequences the application deterministically (the
        # cancel-vs-finish race's chaos handle) — assert it fired.
        queued = Request(prompts[1], 6, ticket_id="queued")
        plan = FaultPlan(seed=5).slow_cancel(0.01, at=1)
        with plan:
            results = eng.run([victim, survivor, queued], results=True)
        assert ("engine.cancel" in [s for s, _, _ in plan.fired])
        assert results[0].status == "cancelled"
        assert 2 <= len(results[0].tokens) < 8  # partial tokens kept
        assert results[1].status == "ok"
        assert results[1].tokens.tolist() == gold.tokens.tolist()
        assert results[2].status == "cancelled"
        assert len(results[2].tokens) == 0  # never admitted
        assert eng.stats["cancelled_requests"] == 2
        assert eng.stats["failed_requests"] == 0
        assert eng.audit() == []
        # Telemetry: the status label + the cancel events.
        reqs = obs_metrics.default_registry().get("tdt_requests_total")
        assert reqs.value(status="cancelled") == 2
        evts, _ = obs_events.default_ring().tail(kind="cancel")
        assert len(evts) >= 2  # the verb-level + per-request events
    finally:
        mesh_mod.finalize_distributed()


def test_cancel_through_router_by_client_id():
    """Through a Router a client id rides as ``client_tid`` NEXT TO
    the ticket's unique wire id (so reused ids can't conflate a child
    batch): the cancel verb must still find and tear down the
    in-flight request by the id the client holds."""
    from triton_distributed_tpu.serving.router import Router

    eng = StubEngine(num_pages=64, page_size=4, delay_s=2.0)
    router = Router([eng])
    server = ModelServer(router).start()
    try:
        got: list = []
        done = threading.Event()

        def run():
            try:
                for f in request_stream(
                    server.host, server.port,
                    {"requests": [PROMPT], "gen_lens": [40],
                     "ticket_ids": ["rc1"]}, timeout=60,
                ):
                    got.append(f)
            finally:
                done.set()

        threading.Thread(target=run, daemon=True).start()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and len(got) < 2:
            time.sleep(0.01)
        request(server.host, server.port,
                {"cmd": "cancel", "ticket_ids": ["rc1"]})
        assert done.wait(30)
        summary = got[-1]
        assert summary["frame"] == "summary"
        assert summary["results"][0]["status"] == "cancelled"
        assert summary["ticket_ids"] == ["rc1"]  # client id echoed
        assert 0 < len(summary["outputs"][0]) < 40
        assert eng.audit() == [] and _pool_whole(eng)
    finally:
        server.shutdown()


def test_cancel_race_with_finish_is_clean():
    """Cancel racing a slot's natural finish: issued at the LAST
    token, so by the time the engine looks the request already
    finished — the cancel must simply lose (full tokens delivered,
    nothing leaks, audit clean)."""
    eng = StubEngine(num_pages=64, page_size=4)
    from triton_distributed_tpu.models.continuous import Request

    req = Request(np.asarray(PROMPT, np.int32), 4, ticket_id="late")
    req.on_token = (
        lambda i, tok: eng.cancel(["late"]) if i == 3 else None
    )
    [r] = eng.run([req], results=True)
    assert r.status == "ok"
    assert r.tokens.tolist() == stub_generate(PROMPT, 4)
    assert eng.audit() == [] and _pool_whole(eng)


def test_stream_drop_chaos_cancels_and_server_survives():
    """An injected ``stream.send`` drop mid-stream reads as a client
    disconnect: the sink goes broken, the payload's requests cancel
    (pages home), the summary still reports the truth on the (here
    still-healthy) socket, and the NEXT request is served bit-exact —
    the chaos contract."""
    eng, server = _stub_server(delay_s=0.5)
    try:
        plan = FaultPlan(seed=7).drop_stream(at=3)
        with plan:
            frames = list(request_stream(
                server.host, server.port,
                {"requests": [PROMPT], "gen_lens": [40],
                 "ticket_ids": ["d1"]}, timeout=60,
            ))
        assert [s for s, _, _ in plan.fired] == ["stream.send"]
        # Exactly 2 frames made the wire (the 3rd write "failed").
        tokens = [f for f in frames if f.get("frame") == "token"]
        assert len(tokens) == 2
        summary = frames[-1]
        assert summary["frame"] == "summary"
        assert summary["results"][0]["status"] == "cancelled"
        assert len(summary["outputs"][0]) < 40
        assert eng.last_stats["cancelled_requests"] == 1
        assert eng.audit() == [] and _pool_whole(eng)
        # Survivor: a fresh request on a fresh connection, bit-exact.
        r = request(server.host, server.port,
                    {"requests": [PROMPT], "gen_lens": [5]})
        assert r["outputs"][0] == stub_generate(PROMPT, 5)
    finally:
        server.shutdown()


def test_stream_resume_from_snapshot_streams_live(fresh_telemetry):
    """A payload-carried snapshot seeds the stream sink: post-resume
    tokens stream LIVE from the snapshot's index (the client already
    holds the restored prefix), and the summary still carries the
    full output."""
    eng, server = _stub_server()
    try:
        restored = stub_generate(PROMPT, 3)
        snap = {"stub": True, "prompt": list(PROMPT), "out": restored,
                "gen_len": 8, "trace_id": None, "exported_at": 0.0}
        frames = list(request_stream(server.host, server.port, {
            "requests": [PROMPT], "gen_lens": [8],
            "snapshots": [snap],
        }))
        tokens = [f for f in frames if f.get("frame") == "token"]
        summary = frames[-1]
        # Frames start AT the resume index — nothing re-sent, nothing
        # deferred to a summary burst.
        assert [f["i"] for f in tokens] == [3, 4, 5, 6, 7]
        assert summary["outputs"][0] == stub_generate(PROMPT, 8)
        assert summary["results"][0]["status"] == "ok"
    finally:
        server.shutdown()


def test_migrated_results_not_judged(fresh_telemetry):
    """A handoff export (status ``migrated``) is NON-terminal: it must
    not count as an SLO miss — the re-dispatched completion is judged
    exactly once."""
    eng, server = _stub_server()
    try:
        eng.request_handoff()  # the batch exports instead of finishing
        r = request(server.host, server.port,
                    {"requests": [PROMPT], "gen_lens": [6]})
        assert r["results"][0]["status"] == "migrated"
        slo = request(server.host, server.port, {"cmd": "slo"})["slo"]
        cls = slo["classes"]["default"]
        assert cls["missed"] == 0 and cls["met"] == 0
    finally:
        server.shutdown()


# -- load generator --------------------------------------------------------


def test_loadgen_deterministic_and_replayable(tmp_path):
    from perf.loadgen import (
        LoadSpec,
        generate_trace,
        load_trace,
        save_trace,
    )

    spec = LoadSpec(rate=5.0, n_requests=64, cancel_frac=0.25, seed=11)
    t1 = generate_trace(spec)
    t2 = generate_trace(spec)
    assert t1 == t2  # same seed → same trace, byte for byte
    assert t1 != generate_trace(LoadSpec(rate=5.0, n_requests=64,
                                         cancel_frac=0.25, seed=12))
    path = tmp_path / "run.loadtrace.jsonl"
    save_trace(str(path), t1, spec)
    loaded, spec_dict = load_trace(str(path))
    assert loaded == t1
    assert spec_dict["seed"] == 11
    # Zipf head: the most common prefix dominates a uniform share.
    from collections import Counter

    counts = Counter(r["prefix_id"] for r in t1)
    assert counts.most_common(1)[0][1] > len(t1) / spec.prefix_pool * 2
    # Long-tail output lengths stay in bounds; cancels marked.
    assert all(spec.gen_min <= r["gen_len"] <= spec.gen_max for r in t1)
    n_cancel = sum(r["cancel_after"] is not None for r in t1)
    assert 0 < n_cancel < len(t1)
    # Arrivals sorted; bursty process clumps them.
    assert [r["t"] for r in t1] == sorted(r["t"] for r in t1)
    bursty = generate_trace(LoadSpec(rate=5.0, n_requests=32,
                                     process="bursty", burst_size=4,
                                     seed=11))
    gaps = np.diff([r["t"] for r in bursty])
    assert (gaps == 0).sum() >= len(bursty) // 2  # in-burst arrivals


# -- SLO math --------------------------------------------------------------


def _wire_tl(ttft=0.1, n=5, tpot=0.02, status="ok", enq=100.0):
    tl = Timeline()
    tl.enqueue_t = enq
    t = enq + ttft
    for _ in range(n):
        tl.first_token_t = tl.first_token_t or t
        tl.token_ts.append(t)
        t += tpot
    tl.tokens_out = n
    tl.finish_t = None
    tl.status = None
    tl.finish(status)
    # finish() stamped wall time; pin it for deterministic e2e math.
    tl.finish_t = t
    return tl


def test_slo_spec_evaluation_and_goodput(fresh_telemetry):
    reg = obs_metrics.default_registry()
    spec = obs_slo.SLOSpec("interactive", ttft_s=0.2, tpot_s=0.05,
                           e2e_s=1.0)
    assert obs_slo.observe_wire(_wire_tl(), spec, reg) == "met"
    assert obs_slo.observe_wire(_wire_tl(ttft=0.5), spec, reg) == "missed"
    assert obs_slo.observe_wire(
        _wire_tl(tpot=0.2), spec, reg) == "missed"
    # A FAILED request with an unmeasurable deadline counts violated
    # (shedding must not read as goodput)...
    failed = Timeline()
    failed.enqueue_t = 1.0
    failed.finish("overloaded")
    assert obs_slo.observe_wire(failed, spec, reg) == "missed"
    # ...but an OK request missing only inapplicable durations passes
    # on what IS measured (1-token answer: no TPOT).
    one = _wire_tl(n=1)
    assert obs_slo.observe_wire(one, spec, reg) == "met"
    # Cancelled: counted, excluded from the goodput denominator.
    assert obs_slo.observe_wire(
        _wire_tl(status="cancelled"), spec, reg) == "cancelled"
    assert obs_slo.goodput("interactive", reg) == pytest.approx(2 / 5)
    snap = obs_slo.snapshot({"interactive": spec}, reg)
    cls = snap["classes"]["interactive"]
    assert cls["met"] == 2 and cls["missed"] == 3
    assert cls["cancelled"] == 1
    assert cls["violations"]["ttft"] >= 2  # ttft=0.5 + the failed one
    assert cls["ttft_p50_s"] is not None
    assert snap["specs"]["interactive"]["ttft_s"] == 0.2


def test_server_surfaces_slo_spec_and_verb(fresh_telemetry):
    eng = StubEngine(num_pages=64, page_size=4)
    server = ModelServer(
        eng, slo=obs_slo.SLOSpec("default", ttft_s=10.0)
    ).start()
    try:
        stats = request(server.host, server.port, {"cmd": "stats"})
        assert stats["stats"]["server"]["engine"]["slo"]["default"][
            "ttft_s"] == 10.0
        list(request_stream(server.host, server.port,
                            {"requests": [PROMPT], "gen_lens": [4]}))
        slo = request(server.host, server.port, {"cmd": "slo"})["slo"]
        assert slo["classes"]["default"]["met"] == 1
        assert slo["classes"]["default"]["goodput"] == 1.0
    finally:
        server.shutdown()


# -- fleet-scope aggregation -----------------------------------------------

_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="
    r"\"(?:[^\"\\\n]|\\[\\\"n])*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\[\\\"n])*\")*\})? "
    r"-?[0-9.e+-]+(\s[0-9]+)?$"
)


def _parse_series(text: str) -> dict:
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert _SAMPLE.match(line), f"bad exposition line: {line!r}"
        name_labels, value = line.rsplit(" ", 1)
        out[name_labels] = float(value)
    return out


def test_merge_expositions_labels_escaping_and_values():
    from triton_distributed_tpu.obs.metrics import merge_expositions

    a = ("# HELP x_total things\n# TYPE x_total counter\n"
         'x_total{verb="ping"} 3\nx_total{verb="stats"} 1\n'
         "# TYPE h histogram\n"
         'h_bucket{le="1"} 2\nh_bucket{le="+Inf"} 2\n'
         "h_sum 0.5\nh_count 2\n")
    b = ("# HELP x_total things\n# TYPE x_total counter\n"
         'x_total{verb="ping"} 4\n')
    merged = merge_expositions({'r0#2"\\': a, "r1": b}, label="replica")
    series = _parse_series(merged)
    # Replica label injected first, value preserved, escapes legal.
    assert series['x_total{replica="r0#2\\"\\\\",verb="ping"}'] == 3
    assert series['x_total{replica="r1",verb="ping"}'] == 4
    # Histogram children follow their family; sums ride through.
    assert series['h_bucket{replica="r0#2\\"\\\\",le="+Inf"}'] == 2
    assert series['h_sum{replica="r0#2\\"\\\\"}'] == 0.5
    # HELP/TYPE once per family.
    assert merged.count("# TYPE x_total counter") == 1
    # Summing across replica labels reproduces the children's totals.
    ping_sum = sum(v for k, v in series.items()
                   if k.startswith("x_total") and 'verb="ping"' in k)
    assert ping_sum == 7


@needs_procs
def test_fleet_scope_scrape_sums_and_stitched_events():
    """ISSUE-13 acceptance: one fleet-scope scrape returns a valid
    Prometheus exposition whose per-replica series equal the
    children's own scrapes; fleet events come back replica-tagged and
    fleet_seq-stitched."""
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    # round_robin: BOTH children serve (affinity would pin repeats to
    # one); 6-page pools force radix evictions by the 3rd request per
    # child, so the children's own event rings carry prefix_evict
    # events for the stitched stream.
    sup = FleetSupervisor([
        stub_spec(f"r{i}", delay_s=0.0, num_pages=6, page_size=4)
        for i in range(2)
    ], policy="round_robin")
    router = sup.start()
    server = ModelServer(router).start()
    try:
        assert sup.wait_healthy(2, timeout_s=120)
        for k in range(8):
            prompt = [10 * k + j for j in range(1, 9)]
            r = request(server.host, server.port,
                        {"requests": [prompt], "gen_lens": [4]},
                        timeout=120)
            assert r["outputs"][0] == stub_generate(prompt, 4)
        fleet = request(server.host, server.port,
                        {"cmd": "metrics", "scope": "fleet"},
                        timeout=120)
        assert fleet["scope"] == "fleet"
        assert sorted(fleet["replicas"]) == ["r0", "r1"]
        assert fleet["errors"] == {}
        merged = _parse_series(fleet["prometheus"])  # validates grammar
        # Per-replica series must equal each child's OWN scrape (no
        # generation traffic ran in between; the requests-verb counter
        # is stable across the probe scrapes).
        for slot in sup._slots:
            rep = slot.replica
            own = request(rep._remote.host, rep._remote.port,
                          {"cmd": "metrics"}, timeout=120)
            own_series = _parse_series(own["prometheus"])
            key = 'tdt_server_requests_total{verb="requests"}'
            want = own_series.get(key)
            assert want is not None and want >= 1
            got = merged.get(
                f'tdt_server_requests_total{{replica="{rep.name}",'
                f'verb="requests"}}'
            )
            assert got == want, (rep.name, got, want)
        # The front's own series ride along under replica="router";
        # series already carrying a replica label (the router's
        # per-child ledger) keep THEIRS — no duplicate label names.
        assert any(k.startswith('tdt_server_requests_total{'
                                'replica="router"')
                   for k in merged)
        assert not any(k.count('replica="') > 1 for k in merged)
        # Fleet events: replica-tagged, fleet_seq strictly increasing,
        # child events present (the tiny pools evicted), and the
        # per-child cursors page forward (a second scrape re-returns
        # no child events).
        ev = request(server.host, server.port,
                     {"cmd": "events", "scope": "fleet"}, timeout=120)
        rows = ev["events"]
        assert rows, "fleet events empty after traffic"
        seqs = [e["fleet_seq"] for e in rows]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        replicas = {e["replica"] for e in rows}
        assert "router" in replicas
        assert replicas & {"r0", "r1"}, rows
        ev2 = request(server.host, server.port,
                      {"cmd": "events", "scope": "fleet"}, timeout=120)
        ev2_replicas = {e["replica"] for e in ev2["events"]}
        assert "r0" not in ev2_replicas and "r1" not in ev2_replicas
    finally:
        server.shutdown()
        sup.shutdown()
