"""Elastic pool control-plane tests (docs/scale-out.md "Disaggregated
pools & autoscaling"): role-typed replica pools, SLO-aware scheduling,
and the goodput-driven autoscaler.

Layers of evidence:

- the pure half (serving/pools.py): role helpers, the decode placement
  score's match-vs-pressure trade, pool-shape/gauge publication, and
  the Scheduler's priority ordering, token-budget waves, and
  deadline-aware shedding — milliseconds, plain fakes;
- the autoscaler control loop on a FAKE fleet (the duck surface the
  class documents): hysteresis, cooldown, min/max bounds, the
  crash-loop-breaker parked veto, the respawn-in-progress guard, and
  the drain-timeout → deferred-retire path, all via deterministic
  ``tick(now=...)`` calls;
- the router's ``policy="pools"`` on in-process stub replicas: fresh
  work prefills on the prefill pool, hands off, and decodes on the
  decode pool — outputs bit-exact, zero duplicate tokens, the pool
  shape surfaced through stats;
- the batched handoff-sweep export on the tiny model: one
  ``export_slots_batch`` gather produces snapshots IDENTICAL (modulo
  the export wall stamp) to per-slot serial exports, and both resume
  bit-exact;
- CLI guardrails: the pool flags refuse, by flag name, every path
  that would silently ignore them (the PR 12 convention);
- chaos (needs_procs): SIGKILL of a prefill-pool replica mid-handoff
  finishes bit-exact on the decode pool via snapshot reroute; a live
  autoscaler scales a stub fleet UP under a burst and DOWN
  mid-generation with a lossless drain (zero lost/duplicate tokens,
  audits clean).
"""

import signal
import subprocess
import sys
import threading
import time
import types

import numpy as np
import pytest

from triton_distributed_tpu.models.stub import StubEngine, stub_generate
from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.serving import pools
from triton_distributed_tpu.serving.autoscaler import Autoscaler
from triton_distributed_tpu.serving.replica import (
    DRAINED,
    HEALTHY,
    EngineReplica,
)
from triton_distributed_tpu.serving.router import Router


def _can_spawn() -> bool:
    try:
        return subprocess.run(
            [sys.executable, "-c", "pass"], timeout=60
        ).returncode == 0
    except Exception:  # noqa: BLE001 — any failure means "cannot"
        return False


_SPAWN_OK = _can_spawn()
needs_procs = pytest.mark.skipif(
    not _SPAWN_OK or not hasattr(signal, "SIGKILL"),
    reason="child-process spawning unavailable on this platform",
)

STUB_PROMPTS = [
    np.arange(1, 9, dtype=np.int32),
    np.arange(20, 30, dtype=np.int32),
]
STUB_GENS = [50, 40]
STUB_GOLDS = [stub_generate(p, g) for p, g in zip(STUB_PROMPTS, STUB_GENS)]


# -- fakes ------------------------------------------------------------------


class _Rep:
    """The replica duck surface pools.py documents."""

    def __init__(self, name, role, *, pending=0, max_pending=8,
                 free_pages=0, state=HEALTHY):
        self.name = name
        self.role = role
        self.pending = pending
        self.max_pending = max_pending
        self.free_pages = free_pages
        self.state = state
        self.down = False

    def match_len(self, toks):
        return 0


class _FakeRouter:
    def __init__(self, reps):
        self.replicas = reps
        self.stats = {"shed_skips": 0}
        self.drained = []
        self.drain_ok = True

    def drain_replica(self, name, grace_s=None, *, handoff=False):
        self.drained.append((name, handoff))
        for r in self.replicas:
            if r.name == name:
                r.state = DRAINED if self.drain_ok else "draining"
        return self.drain_ok


class _FakeFleet:
    """The fleet duck surface the Autoscaler documents."""

    def __init__(self, reps):
        self.router = _FakeRouter(reps)
        self.parked = set()
        self.fail_spawn = False
        self.added = []
        self.retired = []

    def pool_slots(self, role):
        return [
            {"name": r.name, "parked": r.name in self.parked,
             "down": r.down, "replica_name": r.name,
             "replica_state": r.state, "pending": r.pending}
            for r in self.router.replicas if r.role == role
        ]

    def add_slot(self, spec):
        if self.fail_spawn:
            raise RuntimeError("spawn refused")
        rep = _Rep(spec.name, spec.role)
        self.router.replicas.append(rep)
        self.added.append(spec.name)
        return rep

    def retire_slot(self, name):
        self.retired.append(name)
        self.router.replicas = [
            r for r in self.router.replicas if r.name != name
        ]
        return True


def _spec_factory(role, name):
    return types.SimpleNamespace(role=role, name=name)


class _T:
    """The ticket duck surface Scheduler.plan consumes."""

    def __init__(self, prompt_len, gen_len=8, slo_class=None,
                 snap_out=None, deadline_s=None, enqueue_t=None):
        self.prompt = list(range(1, prompt_len + 1))
        self.gen_len = gen_len
        self.slo_class = slo_class
        self.snapshot = (None if snap_out is None
                         else {"out": list(snap_out)})
        self.deadline_s = deadline_s
        self.enqueue_t = enqueue_t


# -- pure half: roles, scoring, gauges --------------------------------------


def test_role_helpers_and_validation():
    p = _Rep("p", pools.PREFILL)
    d = _Rep("d", pools.DECODE)
    m = _Rep("m", pools.MIXED)
    legacy = types.SimpleNamespace(pending=0)  # never declared a role
    assert pools.replica_role(legacy) == pools.MIXED
    assert pools.replica_role(types.SimpleNamespace(role="weird")) \
        == pools.MIXED
    assert pools.prefill_capable(p) and not pools.decode_capable(p)
    assert pools.decode_capable(d) and not pools.prefill_capable(d)
    assert pools.prefill_capable(m) and pools.decode_capable(m)
    assert pools.validate_role("prefill") == "prefill"
    with pytest.raises(ValueError, match="role"):
        pools.validate_role("gpu")
    # Occupancy clamps to [0, 1] and survives max_pending=0.
    assert pools.occupancy(_Rep("x", "mixed", pending=4)) == 0.5
    assert pools.occupancy(
        _Rep("x", "mixed", pending=99, max_pending=8)) == 1.0
    assert pools.occupancy(
        _Rep("x", "mixed", pending=1, max_pending=0)) == 1.0


def test_decode_score_weighs_match_against_pressure():
    idle = _Rep("idle", pools.DECODE, pending=0, free_pages=10)
    busy = _Rep("busy", pools.DECODE, pending=8, free_pages=0)
    # A saturated replica with a PERFECT match still beats an idle one
    # with none (2*1 - 1 > 0)...
    assert pools.decode_score(busy, 10, 10) \
        > pools.decode_score(idle, 0, 10)
    # ...but a SHORT match loses to idleness: pressure breaks
    # monopolies (2*0.3 - 1 < 0).
    assert pools.decode_score(busy, 3, 10) \
        < pools.decode_score(idle, 0, 10)
    # The free-page term breaks ties between equal matches and is
    # normalized by the pool max (and disabled when max_free == 0).
    a = _Rep("a", pools.DECODE, pending=0, free_pages=10)
    b = _Rep("b", pools.DECODE, pending=0, free_pages=2)
    assert pools.decode_score(a, 5, 10, max_free=10) \
        > pools.decode_score(b, 5, 10, max_free=10)
    assert pools.decode_score(a, 5, 10) == pools.decode_score(b, 5, 10)


def test_pool_shape_and_gauges(fresh_telemetry):
    reps = [
        _Rep("p0", pools.PREFILL, pending=4, free_pages=8),
        _Rep("p1", pools.PREFILL, pending=2, free_pages=4,
             state="draining"),
        _Rep("d0", pools.DECODE, pending=8, free_pages=2),
        _Rep("m0", pools.MIXED),
    ]
    shape = pools.pool_shape(reps)
    assert shape["prefill"] == {"replicas": 2, "healthy": 1}
    assert shape["decode"] == {"replicas": 1, "healthy": 1}
    assert shape["mixed"] == {"replicas": 1, "healthy": 1}
    reg = obs_metrics.default_registry()
    out = pools.publish_pool_gauges(reps, reg)
    # Healthy replicas only: the draining p1 is not capacity.
    assert out["prefill"] == {"replicas": 1, "pending": 4,
                              "free_pages": 8, "occupancy": 0.5}
    assert out["decode"]["occupancy"] == 1.0
    g = reg.get("tdt_pool_occupancy")
    assert g.value(role="prefill") == 0.5
    assert g.value(role="decode") == 1.0
    assert reg.get("tdt_pool_replicas").value(role="prefill") == 1
    assert reg.get("tdt_pool_free_pages").value(role="decode") == 2


# -- scheduler --------------------------------------------------------------


def test_scheduler_priority_and_budget_waves():
    sched = pools.Scheduler(class_priority={"gold": 0, "bulk": 1},
                            prefill_token_budget=8,
                            decode_token_budget=5)
    bulk = _T(6, slo_class="bulk")
    gold = _T(4, slo_class="gold")
    unknown = _T(2, slo_class="other")  # ranks after every named class
    waves, shed = sched.plan([bulk, gold, unknown], now=0.0)
    assert shed == []
    # gold runs first; bulk(6) would blow the 8-token budget after
    # gold(4), so it defers; unknown(2) back-fills... no — waves are
    # greedy IN ORDER, so unknown rides the second wave with bulk.
    assert waves[0] == [gold]
    assert waves[1] == [bulk, unknown]
    # An oversize ticket still gets a wave of its own: budgets pace,
    # they never starve.
    huge = _T(50)
    waves, _ = sched.plan([_T(3), huge], now=0.0)
    assert [len(w) for w in waves] == [1, 1] and waves[1] == [huge]
    # Snapshot tickets cost their REMAINING generation against the
    # decode budget: 8-gen with 5 already out costs 3, twice fits the
    # 5-token decode budget only once.
    s1 = _T(4, gen_len=8, snap_out=[1, 2, 3, 4, 5])
    s2 = _T(4, gen_len=8, snap_out=[1, 2, 3, 4, 5])
    waves, _ = sched.plan([s1, s2], now=0.0)
    assert [len(w) for w in waves] == [1, 1]
    # Zero budgets = no pacing at all.
    waves, _ = pools.Scheduler().plan([_T(100), _T(100)], now=0.0)
    assert [len(w) for w in waves] == [2]


def test_scheduler_sheds_past_deadline(fresh_telemetry):
    sched = pools.Scheduler()
    dead = _T(4, slo_class="bulk", deadline_s=0.5, enqueue_t=10.0)
    alive = _T(4, deadline_s=100.0, enqueue_t=10.0)
    unstamped = _T(4, deadline_s=0.5)  # no enqueue stamp: never shed
    waves, shed = sched.plan([dead, alive, unstamped], now=20.0)
    assert shed == [dead]
    assert waves == [[alive, unstamped]]
    reg = obs_metrics.default_registry()
    sched.record_plan(waves, shed, reg)
    assert reg.get("tdt_pool_sched_shed_total").value(
        slo_class="bulk") == 1
    evts, _ = obs_events.default_ring().tail(kind="sched_shed")
    assert evts and evts[-1].fields["count"] == 1
    assert evts[-1].fields["classes"] == ["bulk"]
    # Deferred counter: everything past the first wave.
    sched2 = pools.Scheduler(prefill_token_budget=4)
    waves, shed = sched2.plan([_T(4), _T(4), _T(4)], now=0.0)
    sched2.record_plan(waves, shed, reg)
    assert reg.get("tdt_pool_sched_deferred_total").value() == 2


# -- autoscaler on the fake fleet -------------------------------------------


def test_autoscaler_scale_up_cooldown_and_max(fresh_telemetry):
    fleet = _FakeFleet([_Rep("p0", pools.PREFILL, pending=8)])
    scaler = Autoscaler(fleet, _spec_factory,
                        pool_bounds={"prefill": (1, 3)},
                        cooldown_s=4.0, down_ticks=2)
    d = scaler.tick(now=0.0)
    assert [x["action"] for x in d] == ["scale_up"]
    assert fleet.added == ["prefill-as1"]
    # Keep the pool hot so the next intent is still "up".
    fleet.router.replicas[-1].pending = 8
    d = scaler.tick(now=1.0)
    assert [x["action"] for x in d] == ["skip"]
    assert d[0]["reason"] == "cooldown"
    d = scaler.tick(now=5.0)
    assert [x["action"] for x in d] == ["scale_up"]
    fleet.router.replicas[-1].pending = 8
    d = scaler.tick(now=10.0)
    assert d[0]["reason"] == "at_max"
    reg = obs_metrics.default_registry()
    assert reg.get("tdt_autoscaler_decisions_total").value(
        action="scale_up", role="prefill") == 2
    assert reg.get("tdt_autoscaler_skips_total").value(
        reason="cooldown") == 1
    assert reg.get("tdt_autoscaler_pool_size").value(role="prefill") == 3
    evts, _ = obs_events.default_ring().tail(kind="autoscale")
    assert sum(e.fields["action"] == "scale_up" for e in evts) == 2
    assert scaler.stats["scale_ups"] == 2 and scaler.stats["skips"] == 2


def test_autoscaler_scale_down_hysteresis_and_min(fresh_telemetry):
    fleet = _FakeFleet([
        _Rep("d0", pools.DECODE, pending=0),
        _Rep("d1", pools.DECODE, pending=1),
    ])
    scaler = Autoscaler(fleet, _spec_factory,
                        pool_bounds={"decode": (1, 3)},
                        cooldown_s=0.0, down_ticks=2)
    # Hysteresis: one calm tick is not enough.
    assert scaler.tick(now=0.0) == []
    d = scaler.tick(now=1.0)
    assert [x["action"] for x in d] == ["scale_down"]
    # Victim = least-pending healthy; drained synchronously → retired.
    assert d[0]["replica"] == "d0" and d[0]["drained"] is True
    assert fleet.router.drained == [("d0", True)]
    assert fleet.retired == ["d0"]
    # At the floor: calm ticks now skip with at_min.
    scaler.tick(now=2.0)
    d = scaler.tick(now=3.0)
    assert d and d[0]["reason"] == "at_min"
    reg = obs_metrics.default_registry()
    assert reg.get("tdt_autoscaler_decisions_total").value(
        action="scale_down", role="decode") == 1


def test_autoscaler_drain_timeout_defers_retire(fresh_telemetry):
    fleet = _FakeFleet([
        _Rep("d0", pools.DECODE, pending=0),
        _Rep("d1", pools.DECODE, pending=0),
    ])
    fleet.router.drain_ok = False  # drain "times out": still draining
    scaler = Autoscaler(fleet, _spec_factory,
                        pool_bounds={"decode": (1, 2)},
                        cooldown_s=0.0, down_ticks=1)
    d = scaler.tick(now=0.0)
    assert d[0]["action"] == "scale_down" and d[0]["drained"] is False
    assert fleet.retired == []  # in-flight work is never killed
    # The victim's worker finishes draining; the next tick reaps it.
    for r in fleet.router.replicas:
        if r.name == d[0]["replica"]:
            r.state = DRAINED
    d2 = scaler.tick(now=1.0)
    assert {"action": "retired", "role": "decode",
            "replica": d[0]["replica"]} in d2
    assert fleet.retired == [d[0]["replica"]]


def test_autoscaler_parked_and_respawn_vetoes(fresh_telemetry):
    # Parked slot: the crash-loop breaker owns this pool — scale-up
    # must not fight it.
    fleet = _FakeFleet([
        _Rep("p0", pools.PREFILL, pending=8),
        _Rep("p1", pools.PREFILL, pending=8),
    ])
    fleet.parked.add("p1")
    scaler = Autoscaler(fleet, _spec_factory,
                        pool_bounds={"prefill": (1, 4)},
                        cooldown_s=0.0, down_ticks=1)
    d = scaler.tick(now=0.0)
    assert d[0] == {"action": "skip", "role": "prefill",
                    "reason": "parked"}
    assert fleet.added == []
    # A slot mid-respawn: adding capacity would race the supervisor.
    fleet.parked.clear()
    fleet.router.replicas[1].down = True
    d = scaler.tick(now=1.0)
    assert d[0]["reason"] == "respawn_in_progress"
    # Spawn failure is data, not an exception out of the loop.
    fleet.router.replicas[1].down = False
    fleet.fail_spawn = True
    d = scaler.tick(now=2.0)
    assert d[0]["reason"] == "spawn_failed:RuntimeError"
    reg = obs_metrics.default_registry()
    assert reg.get("tdt_autoscaler_skips_total").value(
        reason="parked") == 1
    assert scaler.stats["scale_ups"] == 0


def test_autoscaler_validates_bounds_and_thresholds():
    fleet = _FakeFleet([])
    with pytest.raises(ValueError, match="role"):
        Autoscaler(fleet, _spec_factory, pool_bounds={"gpu": (1, 2)})
    with pytest.raises(ValueError, match="bounds"):
        Autoscaler(fleet, _spec_factory, pool_bounds={"mixed": (3, 1)})
    with pytest.raises(ValueError, match="occupancy"):
        Autoscaler(fleet, _spec_factory, pool_bounds={"mixed": (1, 2)},
                   up_occupancy=0.2, down_occupancy=0.5)


def test_autoscaler_urgency_overrides_calm_occupancy(fresh_telemetry):
    """SLO violations and router shed-skips force the scale-up path
    even when raw occupancy reads calm: TTFT indicts prefill,
    TPOT/e2e the decode pool."""
    reg = obs_metrics.default_registry()
    viol = reg.counter(
        "tdt_slo_violations_total",
        "Per-deadline SLO violations.", labels=("slo_class", "deadline"))
    fleet = _FakeFleet([
        _Rep("p0", pools.PREFILL, pending=0),
        _Rep("d0", pools.DECODE, pending=0),
    ])
    scaler = Autoscaler(fleet, _spec_factory,
                        pool_bounds={"prefill": (1, 2),
                                     "decode": (1, 2)},
                        cooldown_s=0.0, down_ticks=99)
    assert scaler.tick(now=0.0) == []  # calm fleet, no violations
    viol.inc(slo_class="default", deadline="ttft")
    d = scaler.tick(now=1.0)
    assert [(x["action"], x["role"]) for x in d] == [
        ("scale_up", "prefill")]
    viol.inc(slo_class="default", deadline="tpot")
    d = scaler.tick(now=2.0)
    assert [(x["action"], x["role"]) for x in d] == [
        ("scale_up", "decode")]
    # Deltas, not totals: a quiet tick after the burst takes no action.
    assert scaler.tick(now=3.0) == []


# -- router policy="pools" on in-process stubs ------------------------------


def _stub_replica(name, role, *, delay_s=0.0, num_pages=64):
    return EngineReplica(
        StubEngine(num_pages=num_pages, page_size=4, delay_s=delay_s),
        name=name, role=role,
    )


def test_pools_policy_disaggregates_bit_exact(fresh_telemetry):
    """The tentpole's routing half: fresh requests prefill on the
    prefill pool, hand off through the snapshot machinery, and decode
    on the decode pool — outputs bit-exact, zero duplicate tokens."""
    reps = [_stub_replica("p0", "prefill"), _stub_replica("d0", "decode")]
    router = Router(reps, policy="pools", max_reroutes=3)
    res = router.run(list(zip(STUB_PROMPTS, STUB_GENS)), results=True)
    for r, g in zip(res, STUB_GOLDS):
        assert r.status == "ok", (r.status, r.reason)
        assert r.tokens.tolist() == g
    assert router.stats["pool_prefill"] >= 2
    assert router.stats["pool_decode"] >= 2
    assert router.stats["prefill_migrations"] >= 2
    # Zero duplicates: every token generated exactly once fleet-wide
    # (restored tokens count as migrated_in, never re-generated).
    agg = router.last_stats
    assert agg["generated_tokens"] == sum(STUB_GENS)
    assert agg["migrated_in_tokens"] >= 1
    # The pool shape surfaces through the stats path server_stats uses.
    shape = agg["router"]["pools"]
    assert shape["prefill"] == {"replicas": 1, "healthy": 1}
    assert shape["decode"] == {"replicas": 1, "healthy": 1}
    assert router.audit() == []
    router.shutdown()


def test_pools_policy_single_replica_serves_end_to_end():
    """Degraded shapes stay correct: with no decode-capable target the
    prefill replica serves end-to-end (no handoff), roles steer but
    never strand."""
    router = Router([_stub_replica("solo", "prefill")], policy="pools")
    res = router.run([(STUB_PROMPTS[0], 6)], results=True)
    assert res[0].status == "ok"
    assert res[0].tokens.tolist() == stub_generate(STUB_PROMPTS[0], 6)
    assert router.stats["migrations"] == 0  # nowhere to hand off to
    router.shutdown()


def test_pools_decode_placement_prefers_match_then_pressure():
    """Snapshot tickets score onto the decode pool by decode_score:
    the digest-matching replica wins when idle; see
    test_decode_score_weighs_match_against_pressure for the pressure
    flip (exercised pure — replica pending is thread-owned here)."""
    from triton_distributed_tpu.serving.replica import Ticket

    reps = [_stub_replica("d0", "decode"), _stub_replica("d1", "decode")]
    router = Router(reps, policy="pools")
    # Warm d1's radix with the prompt so its digest matches.
    warm = router.replica("d1")
    warm.submit(Ticket(STUB_PROMPTS[0], 4))
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not warm.match_len(
            [int(t) for t in STUB_PROMPTS[0]]):
        time.sleep(0.01)
    assert warm.match_len([int(t) for t in STUB_PROMPTS[0]]) > 0
    t = Ticket(STUB_PROMPTS[0], STUB_GENS[0])
    t.snapshot = {"stub": True, "prompt": [int(x) for x in
                                           STUB_PROMPTS[0]],
                  "out": stub_generate(STUB_PROMPTS[0], 3),
                  "gen_len": STUB_GENS[0], "trace_id": None,
                  "exported_at": 0.0}
    rep, matched, decision = router._pick(t)
    assert decision == "pool_decode"
    assert rep.name == "d1" and matched > 0
    router.shutdown()


def test_router_scheduler_sheds_past_deadline_before_dispatch(
        fresh_telemetry):
    """Router.run with a Scheduler completes already-past-SLO tickets
    as deadline_exceeded WITHOUT spending a dispatch hop; everything
    else serves bit-exact."""
    from triton_distributed_tpu.models.continuous import Request
    from triton_distributed_tpu.obs.timeline import Timeline

    sched = pools.Scheduler(class_priority={"gold": 0, "bulk": 1})
    router = Router([_stub_replica("m0", "mixed")], policy="affinity",
                    scheduler=sched)
    tl = Timeline()
    tl.enqueue_t = time.monotonic() - 10.0  # enqueued long ago
    dead = Request(STUB_PROMPTS[0], 6, deadline_s=0.01, timeline=tl,
                   slo_class="bulk")
    live = Request(STUB_PROMPTS[1], 6, slo_class="gold")
    res = router.run([dead, live], results=True)
    assert res[0].status == "deadline_exceeded"
    assert "shed by pool scheduler" in res[0].reason
    assert len(res[0].tokens) == 0
    assert res[1].status == "ok"
    assert res[1].tokens.tolist() == stub_generate(STUB_PROMPTS[1], 6)
    assert router.stats["sched_sheds"] == 1
    assert router.stats["routed"] == 1  # the shed ticket never routed
    reg = obs_metrics.default_registry()
    assert reg.get("tdt_pool_sched_shed_total").value(
        slo_class="bulk") == 1
    router.shutdown()


# -- loadgen class mix ------------------------------------------------------


def test_loadgen_class_mix_deterministic_and_trace_compatible():
    from perf.loadgen import LoadSpec, generate_trace

    mix = (("gold", 1.0), ("bulk", 3.0))
    spec = LoadSpec(rate=5.0, n_requests=80, seed=3, class_mix=mix)
    t1 = generate_trace(spec)
    assert t1 == generate_trace(spec)  # seeded, replay-identical
    counts = {}
    for row in t1:
        counts[row["slo_class"]] = counts.get(row["slo_class"], 0) + 1
    assert set(counts) == {"gold", "bulk"}
    assert counts["bulk"] > counts["gold"]  # 3:1 weighting shows
    # Trace-identity contract: a mix-less spec's trace is bit-identical
    # to the mixed one everywhere EXCEPT slo_class (class draws come
    # after every pre-existing rng draw).
    base = generate_trace(LoadSpec(rate=5.0, n_requests=80, seed=3))
    for a, b in zip(base, t1):
        a2, b2 = dict(a), dict(b)
        a2.pop("slo_class"), b2.pop("slo_class")
        assert a2 == b2
    assert all(r["slo_class"] == "default" for r in base)
    with pytest.raises(ValueError, match="class_mix"):
        generate_trace(LoadSpec(n_requests=4,
                                class_mix=(("x", 0.0),)))


# -- stub capacity model ----------------------------------------------------


def test_stub_max_batch_capacity_model():
    """``max_batch`` bounds the stub's per-round decode slots: an
    over-cap batch costs one delay_s per chunk (finite replica
    throughput — what perf/pools_bench.py saturates), while tokens
    stay bit-exact and cap-independent."""
    import time as _time

    from triton_distributed_tpu.models.stub import (
        StubEngine,
        stub_generate,
    )

    reqs = [(STUB_PROMPTS[0], 5)] * 8
    gold = stub_generate(STUB_PROMPTS[0], 5)

    t0 = _time.perf_counter()
    outs = StubEngine(delay_s=0.15).run(reqs)
    one_round = _time.perf_counter() - t0
    assert all(list(o) == gold for o in outs)

    capped = StubEngine(delay_s=0.15, max_batch=2)
    t0 = _time.perf_counter()
    outs = capped.run(reqs)
    four_rounds = _time.perf_counter() - t0
    assert all(list(o) == gold for o in outs)
    # 8 requests / cap 2 = 4 rounds of wall floor vs 1 uncapped.
    assert four_rounds > 3 * 0.15 > one_round
    assert capped.run([]) == []

    with pytest.raises(ValueError, match="max_batch"):
        StubEngine(max_batch=-1)


# -- CLI guardrails ---------------------------------------------------------


def test_serving_cli_pool_flag_guardrails():
    """Both serving CLIs refuse the pool flags, by flag name and
    BEFORE loading anything, on every path that would silently ignore
    them (the PR 12 --tier-* convention)."""
    import os

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from perf import serve_demo
    from triton_distributed_tpu.serving import run_server

    common = [
        # One role without the other: nowhere to hand prefills.
        ["--model", "stub", "--prefill-replicas", "1"],
        ["--model", "stub", "--decode-replicas", "1"],
        # The pool flags size the fleet themselves.
        ["--model", "stub", "--prefill-replicas", "1",
         "--decode-replicas", "1", "--fleet", "2"],
        # In-process --replicas would drop the role tags.
        ["--model", "stub", "--prefill-replicas", "1",
         "--decode-replicas", "1", "--replicas", "2"],
        # --autoscale without a pool fleet has nothing to resize.
        ["--model", "stub", "--autoscale"],
    ]
    for main in (serve_demo.main, run_server.main):
        for flags in common:
            with pytest.raises(SystemExit) as ei:
                main(flags)
            assert ei.value.code == 2, flags  # argparse p.error
    # run_server only: an explicit non-pools policy ignores the roles.
    with pytest.raises(SystemExit) as ei:
        run_server.main(["--model", "stub", "--prefill-replicas", "1",
                         "--decode-replicas", "1",
                         "--policy", "round_robin"])
    assert ei.value.code == 2


# -- batched handoff export (tiny model) ------------------------------------


@pytest.fixture(scope="module")
def pool_model():
    import jax

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(
        tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    yield model
    mesh_mod.finalize_distributed()


MODEL_PROMPTS = [
    np.arange(1, 20, dtype=np.int32),
    np.arange(30, 42, dtype=np.int32),
]
MODEL_GENS = [12, 10]


def _model_engine(model, **kw):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 16)
    kw.setdefault("prefix_cache", True)
    return ContinuousEngine(model, **kw)


def test_batched_handoff_export_matches_serial(pool_model, monkeypatch):
    """The handoff-batching satellite: one export_slots_batch gather
    over a sweep's slots produces snapshots IDENTICAL (modulo the
    export wall stamp) to per-slot serial exports, and the batched
    snapshots resume bit-exact."""
    from triton_distributed_tpu.models import slot_state
    from triton_distributed_tpu.models.continuous import Request

    work = list(zip(MODEL_PROMPTS, MODEL_GENS))
    golds = [r.tokens.tolist() for r in
             _model_engine(pool_model).run(work, results=True)]
    calls = []
    orig = slot_state.export_slots_batch
    monkeypatch.setattr(
        slot_state, "export_slots_batch",
        lambda eng, slots, **kw: (calls.append(list(slots)),
                                  orig(eng, slots, **kw))[1])
    snaps = {}
    for batched in (True, False):
        eng = _model_engine(pool_model, handoff_batch=batched)
        eng.request_handoff(after_rounds=3)
        res = eng.run(work, results=True)
        assert all(r.status == "migrated" for r in res), [
            (r.status, r.reason) for r in res
        ]
        assert eng.audit() == []
        snaps[batched] = [r.snapshot for r in res]
    assert len(calls) == 1 and len(calls[0]) == 2  # one sweep, 2 slots
    # Bit-identical wire payloads modulo the export wall stamp and the
    # engine-global trace counter (fresh per engine by design).
    for sb, ss in zip(snaps[True], snaps[False]):
        db, ds = dict(sb), dict(ss)
        for k in ("exported_at", "trace_id"):
            db.pop(k), ds.pop(k)
        assert db == ds
    # And the batched snapshots resume bit-exact.
    B = _model_engine(pool_model)
    res2 = B.run([Request(p, g, snapshot=s)
                  for (p, g), s in zip(work, snaps[True])], results=True)
    for r, g in zip(res2, golds):
        assert r.status == "ok" and r.tokens.tolist() == g
    assert B.audit() == []


def test_handoff_sweep_degrades_to_serial_on_batch_failure(
        pool_model, monkeypatch):
    """A failing batch gather must not fail the drain: the sweep
    degrades to per-slot serial exports and stays lossless."""
    from triton_distributed_tpu.models import slot_state
    from triton_distributed_tpu.models.continuous import Request

    monkeypatch.setattr(
        slot_state, "export_slots_batch",
        lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("boom")))
    work = list(zip(MODEL_PROMPTS, MODEL_GENS))
    eng = _model_engine(pool_model, handoff_batch=True)
    eng.request_handoff(after_rounds=3)
    res = eng.run(work, results=True)
    assert all(r.status == "migrated" for r in res)
    assert eng.audit() == []
    B = _model_engine(pool_model)
    res2 = B.run([Request(p, g, snapshot=r.snapshot)
                  for (p, g), r in zip(work, res)], results=True)
    golds = [r.tokens.tolist() for r in
             _model_engine(pool_model).run(work, results=True)]
    for r, g in zip(res2, golds):
        assert r.status == "ok" and r.tokens.tolist() == g


# -- chaos: live fleets -----------------------------------------------------


def _pool_specs(delay_s):
    from triton_distributed_tpu.serving.supervisor import stub_spec

    return [
        stub_spec("p0", delay_s=delay_s, page_size=4, num_pages=64,
                  role="prefill"),
        stub_spec("d0", delay_s=delay_s, page_size=4, num_pages=64,
                  role="decode"),
        stub_spec("d1", delay_s=delay_s, page_size=4, num_pages=64,
                  role="decode"),
    ]


@needs_procs
def test_pools_fleet_sigkill_prefill_mid_handoff(fresh_telemetry):
    """Chaos-under-elasticity: SIGKILL the prefill-pool replica while
    requests are mid prefill/handoff — the decode pool finishes every
    request bit-exact via snapshot reroute, survivors audit clean."""
    from triton_distributed_tpu.runtime.faults import FaultPlan
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    sup = FleetSupervisor(
        _pool_specs(delay_s=1.2), policy="pools",
        heartbeat_s=0.05, heartbeat_timeout_s=2.0,
        respawn_backoff_s=0.2, spawn_timeout_s=120.0,
        snapshot_s=0.05,
    )
    try:
        router = sup.start()
        plan = FaultPlan(seed=11).kill_proc(replica="p0", after_s=0.4)
        with plan:
            res = router.run(
                list(zip(STUB_PROMPTS, STUB_GENS)), results=True
            )
        assert plan.fired and plan.fired[0][0] == "proc.kill"
        for r, g in zip(res, STUB_GOLDS):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == g
        # The decode pool did the finishing: scored pool_decode hops
        # landed (post-handoff or post-reroute).
        assert router.stats["pool_decode"] >= 1
        assert router.audit() == []
    finally:
        sup.shutdown()


@needs_procs
def test_autoscaler_live_scale_up_and_lossless_scale_down(
        fresh_telemetry):
    """The live elasticity loop: a burst saturates the one-replica
    fleet and a tick scales UP through the supervisor's spawn path
    (the new child joins routing); with the pool calm but work still
    in flight, a tick scales DOWN via the lossless handoff drain —
    zero lost or duplicate tokens, audits clean, decisions visible as
    ``autoscale`` events."""
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        stub_spec,
    )

    def spec(name, role="mixed"):
        return stub_spec(name, delay_s=2.0, page_size=4, num_pages=64,
                         role=role)

    sup = FleetSupervisor(
        [spec("m0")], heartbeat_s=0.05, heartbeat_timeout_s=10.0,
        respawn_backoff_s=0.2, spawn_timeout_s=120.0,
    )
    scaler = None
    try:
        router = sup.start()
        scaler = Autoscaler(
            sup, lambda role, name: spec(name, role),
            pool_bounds={"mixed": (1, 2)},
            cooldown_s=0.0, down_ticks=1,
            up_occupancy=0.6, down_occupancy=0.3,
            drain_grace_s=60.0,
        )
        # Phase 1 — burst: 6 long requests pile onto m0 (max_pending
        # 8 → occupancy 0.75 ≥ 0.6).
        burst = [(np.arange(10 * i + 1, 10 * i + 7, dtype=np.int32), 8)
                 for i in range(6)]
        out = {}

        def run_burst():
            out["burst"] = router.run(burst, results=True)

        th = threading.Thread(target=run_burst, daemon=True)
        th.start()
        deadline = time.monotonic() + 60
        while (time.monotonic() < deadline
               and router.replicas[0].pending < 5):
            time.sleep(0.01)
        assert router.replicas[0].pending >= 5
        d1 = scaler.tick()
        assert any(x["action"] == "scale_up" for x in d1), d1
        assert len(sup.stats()["slots"]) == 2
        assert len(router.replicas) == 2  # joined routing
        th.join(120)
        for (p, g), r in zip(burst, out["burst"]):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == stub_generate(p, g)
        # Phase 2 — calm but mid-generation: two long requests spread
        # over the two replicas (occupancy 0.125 ≤ 0.3); the calm tick
        # drains the least-loaded replica losslessly while its slot is
        # still generating.
        def run_tail():
            out["tail"] = router.run(
                list(zip(STUB_PROMPTS, STUB_GENS)), results=True)

        th2 = threading.Thread(target=run_tail, daemon=True)
        th2.start()
        deadline = time.monotonic() + 60
        while (time.monotonic() < deadline
               and sum(r.pending for r in router.replicas) < 2):
            time.sleep(0.01)
        d2 = scaler.tick()
        downs = [x for x in d2 if x["action"] == "scale_down"]
        assert downs and downs[0]["drained"] is True, d2
        th2.join(120)
        for r, g in zip(out["tail"], STUB_GOLDS):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == g
        # Zero duplicates fleet-wide: every token generated exactly
        # once (handoff-restored tokens count migrated_in, never
        # re-generated) — the lossless-drain ledger.
        agg = router.last_stats
        total = sum(g for _, g in burst) + sum(STUB_GENS)
        assert agg["generated_tokens"] == total
        assert len(sup.stats()["slots"]) == 1  # victim retired
        assert router.audit() == []
        evts, _ = obs_events.default_ring().tail(kind="autoscale")
        actions = {e.fields["action"] for e in evts}
        assert {"scale_up", "scale_down"} <= actions
        evts, _ = obs_events.default_ring().tail(kind="slot_retired")
        assert evts
        assert scaler.stats["scale_ups"] >= 1
        assert scaler.stats["scale_downs"] >= 1
    finally:
        if scaler is not None:
            scaler.stop()
        sup.shutdown()
