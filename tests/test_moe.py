"""MoE tests (parity: reference test_ag_moe.py / test_moe_reduce_rs.py /
test_ep_a2a.py — golden = dense per-token expert loop)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.layers.tp_moe import TPMoE
from triton_distributed_tpu.ops.moe import (
    ep_moe_ffn,
    grouped_ffn,
    moe_combine,
    moe_sort,
    router_topk,
)


def _golden_moe(x, w_router, gate, up, down, k, norm=True):
    """Dense reference: route each token, run its experts, weighted sum."""
    logits = np.asarray(x, np.float64) @ np.asarray(w_router, np.float64)
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    t, e = probs.shape
    out = np.zeros((t, x.shape[1]))
    for i in range(t):
        ids = np.argsort(-probs[i])[:k]
        w = probs[i][ids]
        if norm:
            w = w / w.sum()
        for j, eid in zip(w, ids):
            h = np.asarray(x[i], np.float64)
            g = h @ np.asarray(gate[eid], np.float64)
            u = h @ np.asarray(up[eid], np.float64)
            act = g / (1 + np.exp(-g)) * u
            out[i] += j * (act @ np.asarray(down[eid], np.float64))
    return out


@pytest.fixture
def moe_weights(rng):
    e, d, f, k = 8, 32, 64, 2
    mk = lambda *s: jnp.asarray(rng.standard_normal(s) * 0.1, jnp.float32)
    return dict(
        e=e, d=d, f=f, k=k,
        w_router=mk(d, e), gate=mk(e, d, f), up=mk(e, d, f), down=mk(e, f, d),
    )


def test_routing_and_grouped_ffn(rng, moe_weights):
    """Single-device sort + grouped FFN matches the dense loop."""
    mw = moe_weights
    t = 16
    x = jnp.asarray(rng.standard_normal((t, mw["d"])) * 0.1, jnp.float32)
    route = router_topk(x, mw["w_router"], mw["k"])
    st = moe_sort(route, mw["e"])
    w1 = jnp.concatenate([mw["gate"], mw["up"]], axis=2)
    h = grouped_ffn(x[st.token_ids], w1, mw["down"], st.group_sizes)
    out = moe_combine(h, st, t)
    gold = _golden_moe(x, mw["w_router"], mw["gate"], mw["up"], mw["down"], mw["k"])
    np.testing.assert_allclose(np.asarray(out), gold, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("mode", ["xla", "pallas", "ring", "xla_ar", "pallas_ar"])
def test_tp_moe(ctx4, rng, moe_weights, mode):
    mw = moe_weights
    t = 32
    x = jnp.asarray(rng.standard_normal((t, mw["d"])) * 0.1, jnp.float32)
    layer = TPMoE(mw["d"], mw["f"], mw["e"], mw["k"], dtype=jnp.float32, ctx=ctx4)
    layer.load(mw["w_router"], mw["gate"], mw["up"], mw["down"])
    out = layer.forward(x, mode=mode)
    gold = _golden_moe(x, mw["w_router"], mw["gate"], mw["up"], mw["down"], mw["k"])
    np.testing.assert_allclose(np.asarray(out), gold, atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("method", ["xla", "pallas"])
def test_ep_moe(ctx4, rng, moe_weights, method):
    """Experts sharded over 4 ranks; each rank owns 8 local tokens.
    Default (lossless) path must match the dense loop."""
    mw = moe_weights
    t_loc, n = 8, 4
    x = jnp.asarray(rng.standard_normal((n * t_loc, mw["d"])) * 0.1, jnp.float32)
    w1 = jnp.concatenate([mw["gate"], mw["up"]], axis=2)

    f = ctx4.shard_map(
        functools.partial(
            ep_moe_ffn, k=mw["k"], axis="tp", method=method, ctx=ctx4,
        ),
        in_specs=(P("tp", None), P(), P("tp", None, None), P("tp", None, None)),
        out_specs=P("tp", None),
    )
    out = f(x, mw["w_router"], w1, mw["down"])
    gold = _golden_moe(x, mw["w_router"], mw["gate"], mw["up"], mw["down"], mw["k"])
    np.testing.assert_allclose(np.asarray(out), gold, atol=5e-4, rtol=5e-4)


@pytest.mark.parametrize("method", ["xla", "pallas"])
def test_ep_moe_lossless_adversarial(ctx4, rng, moe_weights, method):
    """VERDICT r1 #5: worst-case routing skew — a router biased so EVERY
    token's top-k lands on rank 0's experts — must still be bit-exact vs
    the dense golden, with zero drops (reference never drops;
    ``kernel_get_ag_splits_and_recv_offset`` exchanges real splits)."""
    mw = moe_weights
    t_loc, n = 8, 4
    # Positive tokens + ±100 column bias → every top-k lands on rank 0's
    # experts with certainty (x@(w±100) = x@w ± 100·sum(x), sum(x) > 0).
    x = jnp.asarray(
        np.abs(rng.standard_normal((n * t_loc, mw["d"]))) * 0.1, jnp.float32
    )
    w_router = mw["w_router"].at[:, 2:].add(-100.0).at[:, :2].add(100.0)
    w1 = jnp.concatenate([mw["gate"], mw["up"]], axis=2)

    f = ctx4.shard_map(
        functools.partial(
            ep_moe_ffn, k=mw["k"], axis="tp", method=method, ctx=ctx4,
        ),
        in_specs=(P("tp", None), P(), P("tp", None, None), P("tp", None, None)),
        out_specs=P("tp", None),
    )
    out = f(x, w_router, w1, mw["down"])
    gold = _golden_moe(x, w_router, mw["gate"], mw["up"], mw["down"], mw["k"])
    np.testing.assert_allclose(np.asarray(out), gold, atol=5e-4, rtol=5e-4)


def test_ep_dispatch_overflow_detected(ctx4, rng, moe_weights):
    """Capacity mode must COUNT overflow, not hide it (detected-error
    semantics): adversarial skew at capacity_factor=1.0 reports drops."""
    from triton_distributed_tpu.ops.moe.ep_a2a import ep_dispatch
    from triton_distributed_tpu.ops.moe.routing import router_topk

    mw = moe_weights
    t_loc = 8
    x = jnp.asarray(
        np.abs(rng.standard_normal((4 * t_loc, mw["d"]))) * 0.1, jnp.float32
    )
    w_router = (
        mw["w_router"].at[:, 2:].add(-100.0).at[:, :2].add(100.0)
    )  # all → rank 0

    def body(x_loc):
        route = router_topk(x_loc, w_router, mw["k"])
        # capacity 8 < t_loc*k=16 all targeting rank 0 → drops detected
        _, _, _, state = ep_dispatch(x_loc, route, mw["e"], capacity=8, axis="tp")
        return state.num_dropped[None]

    f = ctx4.shard_map(body, in_specs=P("tp", None), out_specs=P("tp"))
    dropped = f(x)
    assert int(np.asarray(dropped).max()) > 0


@pytest.mark.parametrize("method", ["xla", "pallas"])
def test_ep_moe_fp8_payload(ctx4, rng, moe_weights, method):
    """LL fp8+scales codec (reference low_latency_all_to_all.py:36-125):
    quantized dispatch stays close to the dense golden — over both
    transports, and bit-identically between them (same codec, different
    wire)."""
    mw = moe_weights
    t_loc, n = 8, 4
    x = jnp.asarray(rng.standard_normal((n * t_loc, mw["d"])) * 0.1, jnp.float32)
    w1 = jnp.concatenate([mw["gate"], mw["up"]], axis=2)

    f = ctx4.shard_map(
        functools.partial(
            ep_moe_ffn, k=mw["k"], axis="tp", payload_dtype="fp8",
            method=method, ctx=ctx4,
        ),
        in_specs=(P("tp", None), P(), P("tp", None, None), P("tp", None, None)),
        out_specs=P("tp", None),
    )
    out = f(x, mw["w_router"], w1, mw["down"])
    gold = _golden_moe(x, mw["w_router"], mw["gate"], mw["up"], mw["down"], mw["k"])
    # fp8 payload: ~2^-3 relative mantissa error through one FFN
    np.testing.assert_allclose(np.asarray(out), gold, atol=5e-2, rtol=5e-2)


@pytest.mark.slow
@pytest.mark.parametrize("payload", [None, "fp8"])
def test_ep_transport_parity(ctx4, rng, moe_weights, payload):
    """The device-push transport must be BIT-IDENTICAL to the XLA
    transport (same tokens, same slots, only the wire differs) — at
    skewed splits so partial blocks and empty segments both occur."""
    mw = moe_weights
    t_loc, n = 8, 4
    x = jnp.asarray(
        np.abs(rng.standard_normal((n * t_loc, mw["d"]))) * 0.1, jnp.float32
    )
    # Skew most tokens to rank 0's experts (non-uniform splits).
    w_router = mw["w_router"].at[:, :2].add(50.0)
    w1 = jnp.concatenate([mw["gate"], mw["up"]], axis=2)

    outs = {}
    for method in ("xla", "pallas"):
        f = ctx4.shard_map(
            functools.partial(
                ep_moe_ffn, k=mw["k"], axis="tp", method=method,
                payload_dtype=payload, ctx=ctx4,
            ),
            in_specs=(P("tp", None), P(), P("tp", None, None),
                      P("tp", None, None)),
            out_specs=P("tp", None),
        )
        outs[method] = np.asarray(f(x, w_router, w1, mw["down"]))
    np.testing.assert_array_equal(outs["xla"], outs["pallas"])


def test_ep_moe_capacity_pallas(ctx4, rng, moe_weights):
    """Capacity (bounded-memory) mode over the device-push transport:
    uniform routing under capacity must match the dense golden, and the
    unwritten tail of each segment must not poison the combine."""
    mw = moe_weights
    t_loc, n = 8, 4
    x = jnp.asarray(rng.standard_normal((n * t_loc, mw["d"])) * 0.1, jnp.float32)
    w1 = jnp.concatenate([mw["gate"], mw["up"]], axis=2)

    f = ctx4.shard_map(
        functools.partial(
            ep_moe_ffn, k=mw["k"], axis="tp", method="pallas",
            capacity_factor=4.0, ctx=ctx4,
        ),
        in_specs=(P("tp", None), P(), P("tp", None, None), P("tp", None, None)),
        out_specs=P("tp", None),
    )
    out = f(x, mw["w_router"], w1, mw["down"])
    gold = _golden_moe(x, mw["w_router"], mw["gate"], mw["up"], mw["down"], mw["k"])
    np.testing.assert_allclose(np.asarray(out), gold, atol=5e-4, rtol=5e-4)


def test_qwen3_moe_model(ctx4):
    """Tiny Qwen3-MoE end-to-end: prefill + greedy decode determinism
    (parity: reference test_ep_moe_inference.py)."""
    from triton_distributed_tpu.models import AutoLLM, Engine

    model = AutoLLM.from_pretrained("tiny-moe", ctx=ctx4)
    eng = Engine(model, temperature=0.0, mode="xla")
    prompt = np.arange(8, dtype=np.int32)[None].repeat(2, 0)
    out = eng.serve(prompt, gen_len=3)
    assert out.shape == (2, 11)
    np.testing.assert_array_equal(out[0], out[1])

    # pallas prefill mode must agree with xla mode on the same weights.
    cache_x = model.new_cache(1)
    cache_p = model.new_cache(1)
    toks = jnp.arange(16, dtype=jnp.int32)
    lx, _ = model.prefill(toks, cache_x, "xla")
    lp, _ = model.prefill(toks, cache_p, "pallas")
    np.testing.assert_allclose(np.asarray(lx), np.asarray(lp), atol=2e-4,
                               rtol=2e-4)
