"""Live slot migration tests (docs/scale-out.md "Slot migration &
handoff"): portable in-flight request state, lossless drain handoff,
and snapshot-based crash recovery.

Layers of evidence:

- pure wire-codec and prefix-delta math — milliseconds, no model;
- engine-level bit-exactness on the tiny model (the ISSUE-10
  acceptance core): a request exported mid-generation and imported
  into a SECOND engine produces remaining tokens bit-identical to the
  un-migrated run — bf16 and int8 pools, greedy and seeded sampling,
  with and without a shared radix prefix on the target — pool/radix
  audits clean on both engines (the conftest autouse fixture re-audits
  every live engine after every test);
- kill-mid-migration seams on both ends: a failed export keeps the
  slot decoding locally (handoff stays lossless), a failed import
  falls back to replay-from-prompt (same tokens, counted fallback);
- the serving tier on the deterministic stub: ``handoff=True`` drain
  completes every in-flight request with zero duplicate emissions
  (latch-first tickets), ``migrate_after_prefill`` runs prefill and
  decode on different replicas;
- the chaos layer (needs_procs): a replica process SIGKILLed
  MID-GENERATION with supervisor snapshots enabled resumes victims
  from the last snapshot (tokens-saved counter on the survivor —
  measurably less re-generation than PR 9's replay), a SIGKILL of the
  MIGRATION TARGET re-routes again and still lands bit-exact, and a
  handoff drain over the wire loses nothing.
"""

import dataclasses
import signal
import subprocess
import sys
import threading
import time

import jax
import numpy as np
import pytest

from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.models.stub import StubEngine, stub_generate
from triton_distributed_tpu.runtime import mesh as mesh_mod
from triton_distributed_tpu.runtime.faults import FaultPlan


def _can_spawn() -> bool:
    try:
        return subprocess.run(
            [sys.executable, "-c", "pass"], timeout=60
        ).returncode == 0
    except Exception:  # noqa: BLE001 — any failure means "cannot"
        return False


_SPAWN_OK = _can_spawn()
needs_procs = pytest.mark.skipif(
    not _SPAWN_OK or not hasattr(signal, "SIGKILL"),
    reason="child-process spawning unavailable on this platform",
)


@pytest.fixture(scope="module")
def mig_model():
    """ONE tiny model on a single device for the whole module (the
    test_router.py rationale; tp=1 keeps the page gather/scatter free
    of cross-device sharding concerns — multi-host pools are ROADMAP
    item 1's open half)."""
    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    yield model
    mesh_mod.finalize_distributed()


PROMPTS = [
    np.arange(1, 20, dtype=np.int32),
    np.arange(30, 42, dtype=np.int32),
]
GENS = [12, 10]


def make_engine(model, **kw):
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 16)
    kw.setdefault("prefix_cache", True)
    return ContinuousEngine(model, **kw)


def migrate_run(model, eng_kw, *, after_rounds=4, delta_digest=None,
                reqs=None):
    """Export every request after ``after_rounds`` scheduling rounds on
    engine A, import into a fresh engine B, return (final results,
    stage-1 results, engine B)."""
    from triton_distributed_tpu.models import slot_state
    from triton_distributed_tpu.models.continuous import Request

    A = make_engine(model, **eng_kw)
    A.request_handoff(after_rounds=after_rounds)
    work = reqs or list(zip(PROMPTS, GENS))
    res1 = A.run(work, results=True)
    assert all(r.status == "migrated" for r in res1), [
        (r.status, r.reason) for r in res1
    ]
    assert A.audit() == []
    B = make_engine(model, **eng_kw)
    resume = []
    for (p, g), r in zip(work, res1):
        snap = r.snapshot
        if delta_digest is not None:
            full = slot_state.SlotSnapshot.from_wire(snap)
            thin = slot_state.prefix_delta(full, delta_digest)
            assert thin.from_prefix_pages > full.from_prefix_pages
            assert thin.payload_bytes() < full.payload_bytes()
            snap = thin.to_wire()
        resume.append(Request(p, g, snapshot=snap))
    res2 = B.run(resume, results=True)
    assert B.audit() == []
    return res2, res1, B


# -- pure: wire codec + delta math ----------------------------------------


def test_snapshot_wire_roundtrip_and_validation():
    from triton_distributed_tpu.models.slot_state import (
        SlotSnapshot,
        SnapshotError,
    )

    snap = SlotSnapshot(
        prompt=np.arange(5, dtype=np.int32), out=[7, 8], gen_len=6,
        kv_len=6, page_size=4, kv_dtype="int8",
        k_pages=np.ones((2, 2, 1, 4, 8), np.int8),
        v_pages=np.full((2, 2, 1, 4, 8), 3, np.int8),
        k_scale=np.ones((2, 2, 1), np.float32) * 0.5,
        v_scale=np.ones((2, 2, 1), np.float32),
        key_data=np.asarray([1, 2], np.uint32), key_step=9,
        spec={"k": 3, "proposed": 10, "accepted": 4},
        trace_id="req-x", exported_at=123.5,
    )
    back = SlotSnapshot.from_wire(snap.to_wire())
    np.testing.assert_array_equal(back.prompt, snap.prompt)
    assert back.out == snap.out and back.kv_len == snap.kv_len
    np.testing.assert_array_equal(back.k_pages, snap.k_pages)
    np.testing.assert_array_equal(back.v_scale, snap.v_scale)
    np.testing.assert_array_equal(back.key_data, snap.key_data)
    assert back.key_step == 9 and back.spec["k"] == 3
    assert back.trace_id == "req-x" and back.exported_at == 123.5
    assert back.payload_bytes() == snap.payload_bytes()
    # bf16 pages survive the codec byte-exactly.
    import ml_dtypes

    bf = np.arange(2 * 1 * 1 * 4 * 8, dtype=np.float32).reshape(
        2, 1, 1, 4, 8).astype(ml_dtypes.bfloat16)
    snap2 = dataclasses.replace(
        snap, kv_dtype=None, k_pages=bf, v_pages=bf, k_scale=None,
        v_scale=None,
    )
    back2 = SlotSnapshot.from_wire(snap2.to_wire())
    assert back2.k_pages.dtype == bf.dtype
    np.testing.assert_array_equal(
        back2.k_pages.view(np.uint16), bf.view(np.uint16)
    )
    # Malformed payloads raise SnapshotError (the fallback trigger),
    # never a bare KeyError/ValueError.
    with pytest.raises(SnapshotError):
        SlotSnapshot.from_wire({"prompt": [1]})
    bad = snap.to_wire()
    bad["k_pages"]["b64"] = "!!!not-base64!!!"
    with pytest.raises(SnapshotError):
        SlotSnapshot.from_wire(bad).k_pages  # decode is eager


def test_prefix_delta_math():
    from triton_distributed_tpu.models.slot_state import (
        SlotSnapshot,
        prefix_delta,
    )

    prompt = np.arange(10, dtype=np.int32)
    snap = SlotSnapshot(
        prompt=prompt, out=[50, 51, 52], gen_len=8, kv_len=12,
        page_size=4, kv_dtype=None,
        k_pages=np.zeros((1, 3, 1, 4, 2), np.float32),
        v_pages=np.zeros((1, 3, 1, 4, 2), np.float32),
    )
    assert snap.valid_pages == 3
    assert snap.chain == list(range(10)) + [50, 51]
    # A digest covering the first 8 chain tokens == 2 full pages.
    digest = [[snap.chain[:4], [[snap.chain[4:8], []]]]]
    thin = prefix_delta(snap, digest)
    assert thin.from_prefix_pages == 2
    assert thin.k_pages.shape[1] == 1
    # No coverage → unchanged object.
    assert prefix_delta(snap, []) is snap


# -- engine level: bit-exact migration (the acceptance core) --------------


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_migration_bit_exact_greedy(mig_model, kv_dtype):
    """Exported mid-generation → imported into a second engine →
    remaining greedy tokens bit-identical to the un-migrated run, on
    both pool dtypes; audits clean on both engines."""
    kw = {"kv_dtype": kv_dtype}
    gold = [
        r.tokens.tolist()
        for r in make_engine(mig_model, **kw).run(
            list(zip(PROMPTS, GENS)), results=True
        )
    ]
    res2, res1, B = migrate_run(mig_model, kw)
    assert [r.tokens.tolist() for r in res2] == gold
    # Work actually carried over: stage 1 generated > 0 tokens and the
    # target restored them without re-generating.
    assert all(len(r.tokens) > 0 for r in res1)
    st = B.last_stats
    assert st["migrated_in"] == len(PROMPTS)
    assert st["migrated_in_tokens"] == sum(len(r.tokens) for r in res1)
    assert st["migration_fallbacks"] == 0


def test_migration_bit_exact_seeded_sampling(mig_model):
    """Seeded-sampled continuation is bit-identical too: the snapshot
    carries the per-request PRNG key + draw counter, so the target
    replays the exact draws the source would have made (int8 pool —
    the stricter case)."""
    kw = {"kv_dtype": "int8", "temperature": 0.8, "seed": 11}
    gold = [
        r.tokens.tolist()
        for r in make_engine(mig_model, **kw).run(
            list(zip(PROMPTS, GENS)), results=True
        )
    ]
    res2, _res1, _B = migrate_run(mig_model, kw)
    assert [r.tokens.tolist() for r in res2] == gold
    # And a migrated sampled run is reproducible end to end.
    res3, _, _ = migrate_run(mig_model, kw)
    assert [r.tokens.tolist() for r in res3] == gold


def test_migration_prefix_delta_on_warm_target(mig_model):
    """When the target already caches the prefix (it served the same
    request before), only the non-shared page suffix ships — and the
    continuation stays bit-identical while the import pins the shared
    pages out of the target's radix tree."""
    kw = {"kv_dtype": "int8"}
    gold = [
        r.tokens.tolist()
        for r in make_engine(mig_model, **kw).run(
            list(zip(PROMPTS, GENS)), results=True
        )
    ]
    warm = make_engine(mig_model, **kw)
    warm.run(list(zip(PROMPTS, GENS)), results=True)
    digest = warm.prefix_digest()
    assert digest  # the tree actually holds the chains

    from triton_distributed_tpu.models import slot_state
    from triton_distributed_tpu.models.continuous import Request

    A = make_engine(mig_model, **kw)
    A.request_handoff(after_rounds=4)
    res1 = A.run(list(zip(PROMPTS, GENS)), results=True)
    assert all(r.status == "migrated" for r in res1)
    resume = []
    for (p, g), r in zip(list(zip(PROMPTS, GENS)), res1):
        full = slot_state.SlotSnapshot.from_wire(r.snapshot)
        thin = slot_state.prefix_delta(full, digest)
        assert thin.from_prefix_pages > 0
        assert thin.payload_bytes() < full.payload_bytes()
        resume.append(Request(p, g, snapshot=thin.to_wire()))
    res2 = warm.run(resume, results=True)
    assert [r.tokens.tolist() for r in res2] == gold
    assert warm.last_stats["migration_fallbacks"] == 0
    assert warm.audit() == [] and A.audit() == []


def test_stale_prefix_delta_falls_back_to_replay(mig_model):
    """A prefix-delta snapshot whose omitted pages the target no longer
    caches (fresh tree) cannot be reconstructed: the import falls back
    to a full replay from the prompt — same final tokens, counted
    fallback, clean audits."""
    kw = {"kv_dtype": None}
    gold = [
        r.tokens.tolist()
        for r in make_engine(mig_model, **kw).run(
            list(zip(PROMPTS, GENS)), results=True
        )
    ]
    warm = make_engine(mig_model, **kw)
    warm.run(list(zip(PROMPTS, GENS)), results=True)
    res2, res1, B = migrate_run(
        mig_model, kw, delta_digest=warm.prefix_digest()
    )
    # B's tree is EMPTY — every delta import must have fallen back.
    assert [r.tokens.tolist() for r in res2] == gold
    assert B.last_stats["migration_fallbacks"] == len(PROMPTS)
    assert B.last_stats["migrated_in"] == 0


def test_migration_chaos_seams(mig_model):
    """Kill-mid-migration on either end, deterministically: a failed
    EXPORT keeps the slot decoding locally (the handoff drain stays
    lossless — everything still completes with the right tokens); a
    failed IMPORT falls back to replay-from-prompt (same tokens,
    counted). Audits stay clean on every engine involved."""
    from triton_distributed_tpu.models.continuous import Request

    kw = {"kv_dtype": "int8"}
    gold = [
        r.tokens.tolist()
        for r in make_engine(mig_model, **kw).run(
            list(zip(PROMPTS, GENS)), results=True
        )
    ]
    # Export end dies: every export attempt fails → the handoff sweep
    # can migrate nothing, both requests FINISH on the draining engine.
    A = make_engine(mig_model, **kw)
    A.request_handoff(after_rounds=4)
    with FaultPlan(seed=3).fail_export(at=0, times=999) as plan:
        res = A.run(list(zip(PROMPTS, GENS)), results=True)
    assert plan.fired
    assert [r.status for r in res] == ["ok", "ok"]
    assert [r.tokens.tolist() for r in res] == gold
    assert A.audit() == []

    # Import end dies: the resume falls back to a full replay.
    A2 = make_engine(mig_model, **kw)
    A2.request_handoff(after_rounds=4)
    res1 = A2.run(list(zip(PROMPTS, GENS)), results=True)
    assert all(r.status == "migrated" for r in res1)
    B = make_engine(mig_model, **kw)
    with FaultPlan(seed=4).fail_import(at=0, times=999) as plan:
        res2 = B.run(
            [
                Request(p, g, snapshot=r.snapshot)
                for (p, g), r in zip(list(zip(PROMPTS, GENS)), res1)
            ],
            results=True,
        )
    assert plan.fired
    assert [r.tokens.tolist() for r in res2] == gold
    assert B.last_stats["migration_fallbacks"] == len(PROMPTS)
    assert B.audit() == [] and A2.audit() == []


def test_prefill_only_exports_after_admission(mig_model):
    """``prefill_only`` (the prefill→decode handoff's engine half):
    admission runs, ONE token emits, the slot exports — and a second
    engine finishes the decode bit-identically."""
    from triton_distributed_tpu.models.continuous import Request

    kw = {"kv_dtype": None}
    gold = [
        r.tokens.tolist()
        for r in make_engine(mig_model, **kw).run(
            list(zip(PROMPTS, GENS)), results=True
        )
    ]
    A = make_engine(mig_model, **kw)
    res1 = A.run(
        [Request(p, g, prefill_only=True)
         for p, g in zip(PROMPTS, GENS)],
        results=True,
    )
    assert all(r.status == "migrated" for r in res1)
    assert all(len(r.tokens) == 1 for r in res1)  # the admission token
    B = make_engine(mig_model, **kw)
    res2 = B.run(
        [Request(p, g, snapshot=r.snapshot)
         for (p, g), r in zip(list(zip(PROMPTS, GENS)), res1)],
        results=True,
    )
    assert [r.tokens.tolist() for r in res2] == gold
    assert A.audit() == [] and B.audit() == []


# -- serving tier on the stub: drain handoff + prefill policy -------------


STUB_PROMPTS = [
    np.arange(1, 9, dtype=np.int32),
    np.arange(20, 30, dtype=np.int32),
]
STUB_GENS = [50, 40]
STUB_GOLDS = [stub_generate(p, g) for p, g in zip(STUB_PROMPTS, STUB_GENS)]


def _stub_replicas(n, delay_s=0.0, prefix="r"):
    from triton_distributed_tpu.serving.replica import EngineReplica

    return [
        EngineReplica(
            StubEngine(num_pages=64, page_size=4, delay_s=delay_s),
            name=f"{prefix}{i}",
        )
        for i in range(n)
    ]


def test_handoff_drain_losless_zero_duplicates(fresh_telemetry):
    """ISSUE-10 acceptance: ``handoff=True`` drain completes every
    in-flight request — bit-exact, exactly once (latch-first tickets
    make a duplicate emission structurally impossible; we additionally
    assert the fleet's generated totals count each token once) — and
    the source replica drains cleanly with real work carried over."""
    from triton_distributed_tpu.serving.router import Router

    reps = _stub_replicas(2, delay_s=1.0)
    router = Router(reps, max_reroutes=3)
    out = {}

    def run():
        out["res"] = router.run(
            list(zip(STUB_PROMPTS, STUB_GENS)), results=True
        )

    th = threading.Thread(target=run, daemon=True)
    th.start()
    # Deterministic sync: drain only once a replica has published
    # snapshot progress of >= 3 generated tokens (condition, not sleep).
    deadline = time.monotonic() + 30
    src = None
    while time.monotonic() < deadline and src is None:
        for r in reps:
            if any(
                len(s["out"]) >= 3
                for s in r.engine.export_slots().values()
            ):
                src = r
                break
        time.sleep(0.005)
    assert src is not None, "no replica reached 3 tokens in time"
    assert router.drain_replica(src.name, grace_s=30, handoff=True)
    th.join(60)
    res = out["res"]
    for r, g in zip(res, STUB_GOLDS):
        assert r.status == "ok", (r.status, r.reason)
        assert r.tokens.tolist() == g
    assert src.state == "drained"
    assert router.stats["migrations"] >= 1
    # Zero duplicate emissions: every token counted exactly once
    # across the fleet (restored tokens are NOT re-counted as
    # generated), using the replicas' cumulative totals — last_stats
    # only covers each replica's final batch.
    gen = sum(r.totals["generated_tokens"] for r in reps)
    restored = sum(r.totals["migrated_in_tokens"] for r in reps)
    assert gen == sum(STUB_GENS)
    assert restored >= 3  # the drained slot's progress carried over
    assert router.audit() == []
    router.shutdown()


def test_migrate_after_prefill_policy(fresh_telemetry):
    """The ``migrate_after_prefill`` routing policy: prefill on one
    replica, decode on ANOTHER via the same export/import path —
    outputs bit-exact, both replicas did real work."""
    from triton_distributed_tpu.serving.router import Router

    reps = _stub_replicas(2, prefix="p")
    router = Router(reps, policy="migrate_after_prefill", max_reroutes=3)
    res = router.run(list(zip(STUB_PROMPTS, STUB_GENS)), results=True)
    for r, g in zip(res, STUB_GOLDS):
        assert r.status == "ok", (r.status, r.reason)
        assert r.tokens.tolist() == g
    assert router.stats["prefill_migrations"] >= 1
    # Prefill landed on one replica, decode on the other: both ran.
    assert all(r.runs >= 1 for r in reps)
    # The decode hop landed AWAY from the prefill hop every time.
    assert router.stats["migrations"] == router.stats["prefill_migrations"]
    assert router.audit() == []
    router.shutdown()


def test_stub_snapshot_fallback_on_corrupt_snapshot():
    """A garbled/stale snapshot (mid-transfer corruption) degrades to
    replay: the output is still the full correct generation."""
    from triton_distributed_tpu.serving.replica import Ticket
    from triton_distributed_tpu.serving.router import Router

    reps = _stub_replicas(1, prefix="c")
    router = Router(reps)
    t = Ticket(STUB_PROMPTS[0], STUB_GENS[0])
    t.snapshot = {"prompt": [9, 9, 9], "out": [1, 2]}  # wrong prompt
    router._dispatch(t)
    assert t.wait(30)
    assert t.result.status == "ok"
    assert t.result.tokens.tolist() == STUB_GOLDS[0]
    assert reps[0].engine.last_stats["migration_fallbacks"] == 1
    router.shutdown()


# -- chaos: process fleet (stub children over the wire) -------------------


def _fleet_specs(n, delay_s):
    from triton_distributed_tpu.serving.supervisor import stub_spec

    return [
        stub_spec(f"r{i}", delay_s=delay_s, page_size=4, num_pages=64)
        for i in range(n)
    ]


@needs_procs
def test_fleet_sigkill_snapshot_resume(fresh_telemetry):
    """ISSUE-10 acceptance: SIGKILL mid-generation with supervisor
    snapshots enabled resumes victims from the last snapshot — final
    outputs bit-exact, the snapshot-resume counter fires, and the
    SURVIVOR's tokens-saved counter (scraped through its metrics verb)
    proves measurably fewer tokens were re-generated than PR 9's
    replay recovery (which re-generates all of them)."""
    from triton_distributed_tpu.obs import metrics as obs_metrics
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    sup = FleetSupervisor(
        _fleet_specs(2, delay_s=1.2),
        heartbeat_s=0.05, heartbeat_timeout_s=2.0,
        respawn_backoff_s=0.2, spawn_timeout_s=120.0,
        snapshot_s=0.05,
    )
    try:
        router = sup.start()
        plan = FaultPlan(seed=7).kill_proc(replica="r0", after_s=0.5)
        with plan:
            res = router.run(
                list(zip(STUB_PROMPTS, STUB_GENS)), results=True
            )
        assert plan.fired and plan.fired[0][0] == "proc.kill"
        for r, g in zip(res, STUB_GOLDS):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == g
        snap = obs_metrics.default_registry().snapshot()
        resumes = snap["tdt_supervisor_snapshot_resumes_total"]["series"]
        assert sum(s["value"] for s in resumes) >= 1, resumes
        # Tokens saved, measured ON the serving side: the survivor's
        # import counted every restored token.
        saved = 0
        for rep in router.replicas:
            if rep.state != "healthy":
                continue
            m = rep._remote.call({"cmd": "metrics"})
            series = m["metrics"].get(
                "tdt_migration_tokens_saved_total", {}
            ).get("series", [])
            saved += sum(s["value"] for s in series)
        assert saved >= 1, "snapshot resume saved no generation work"
        assert router.audit() == []
    finally:
        sup.shutdown()


@needs_procs
def test_fleet_sigkill_migration_target(fresh_telemetry):
    """SIGKILL the MIGRATION TARGET: the first kill orphans the ticket
    (it resumes-from-snapshot on a second replica), the second kill
    takes that target down mid-import — the ticket re-routes once more
    and still completes bit-exact; survivors audit clean."""
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    sup = FleetSupervisor(
        _fleet_specs(3, delay_s=1.0),
        heartbeat_s=0.05, heartbeat_timeout_s=2.0,
        respawn_backoff_s=0.2, spawn_timeout_s=120.0,
        snapshot_s=0.05,
    )
    try:
        router = sup.start()
        # Hit 1 = the original batch (killed mid-generation); hit 2 =
        # the re-dispatched, snapshot-carrying batch (the target).
        plan = (FaultPlan(seed=9)
                .kill_proc(replica="r0", after_s=0.4)
                .kill_proc(at=2))
        with plan:
            res = router.run([(STUB_PROMPTS[0], STUB_GENS[0])],
                             results=True)
        assert len(plan.fired) >= 2, plan.fired
        assert res[0].status == "ok", (res[0].status, res[0].reason)
        assert res[0].tokens.tolist() == STUB_GOLDS[0]
        assert router.audit() == []  # survivors clean; dead skipped
    finally:
        sup.shutdown()


@needs_procs
def test_remote_handoff_drain_over_the_wire(fresh_telemetry):
    """Lossless drain across the process boundary: the ``handoff``
    verb stops the child's in-flight batch, its snapshots ride the
    response, and the router re-admits on the survivor — zero tokens
    of work lost, zero duplicates."""
    from triton_distributed_tpu.serving.router import Router

    # Unmanaged remote replicas (no supervisor), the test_fleet.py way.
    from triton_distributed_tpu.serving.supervisor import spawn_replica

    out = {}

    def boot(i, spec):
        out[i] = spawn_replica(spec, spawn_timeout_s=120.0)

    threads = [
        threading.Thread(target=boot, args=(i, s), daemon=True)
        for i, s in enumerate(_fleet_specs(2, delay_s=1.2))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(out) == 2
    reps = [out[0], out[1]]
    router = Router(reps, max_reroutes=3)
    try:
        res_box = {}

        def run():
            res_box["res"] = router.run(
                list(zip(STUB_PROMPTS, STUB_GENS)), results=True
            )

        th = threading.Thread(target=run, daemon=True)
        th.start()
        # Wait for real progress on whichever child holds a batch.
        deadline = time.monotonic() + 30
        src = None
        while time.monotonic() < deadline and src is None:
            for r in reps:
                try:
                    snaps = r.export_slots(timeout=2.0)
                except Exception:  # noqa: BLE001 — child still booting
                    continue
                if any(len(s.get("out") or []) >= 3
                       for s in snaps.values()):
                    src = r
                    break
            time.sleep(0.01)
        assert src is not None, "no child published progress in time"
        assert router.drain_replica(src.name, grace_s=30, handoff=True)
        th.join(60)
        res = res_box["res"]
        for r, g in zip(res, STUB_GOLDS):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == g
        assert router.stats["migrations"] >= 1
        assert src.state == "drained"
        # The survivor restored the drained slot's tokens.
        other = [r for r in reps if r is not src][0]
        m = other._remote.call({"cmd": "metrics"})
        series = m["metrics"].get(
            "tdt_migration_tokens_saved_total", {}
        ).get("series", [])
        assert sum(s["value"] for s in series) >= 3
    finally:
        router.shutdown()
        for r in reps:
            proc = getattr(r, "proc", None)
            if proc is not None and proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)


def test_import_fallback_preserves_seeded_draws(mig_model):
    """Code-review fix: the replay fallback restores the snapshot's
    per-request PRNG key (draw counter reset to 0), so even a FAILED
    import of a seeded-sampled request replays bit-identically to the
    un-migrated run."""
    from triton_distributed_tpu.models.continuous import Request

    kw = {"temperature": 0.8, "seed": 5}
    gold = [
        r.tokens.tolist()
        for r in make_engine(mig_model, **kw).run(
            list(zip(PROMPTS, GENS)), results=True
        )
    ]
    A = make_engine(mig_model, **kw)
    A.request_handoff(after_rounds=4)
    res1 = A.run(list(zip(PROMPTS, GENS)), results=True)
    assert all(r.status == "migrated" for r in res1)
    B = make_engine(mig_model, **kw)
    with FaultPlan(seed=6).fail_import(at=0, times=999) as plan:
        res2 = B.run(
            [Request(p, g, snapshot=r.snapshot)
             for (p, g), r in zip(list(zip(PROMPTS, GENS)), res1)],
            results=True,
        )
    assert plan.fired
    assert B.last_stats["migration_fallbacks"] == len(PROMPTS)
    assert [r.tokens.tolist() for r in res2] == gold


def test_handoff_drain_without_survivors_finishes_locally(
        fresh_telemetry):
    """Code-review fix: ``drain_replica(handoff=True)`` with no OTHER
    healthy replica degrades to the finishing drain — the in-flight
    work completes here instead of being exported into a void."""
    from triton_distributed_tpu.serving.router import Router

    reps = _stub_replicas(1, delay_s=0.5, prefix="solo")
    router = Router(reps, max_reroutes=3)
    out = {}

    def run():
        out["res"] = router.run(
            [(STUB_PROMPTS[0], STUB_GENS[0])], results=True
        )

    th = threading.Thread(target=run, daemon=True)
    th.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and reps[0]._inflight == 0:
        time.sleep(0.005)
    assert router.drain_replica("solo0", grace_s=30, handoff=True)
    th.join(60)
    res = out["res"]
    assert res[0].status == "ok", (res[0].status, res[0].reason)
    assert res[0].tokens.tolist() == STUB_GOLDS[0]
    assert router.stats["migrations"] == 0  # nothing was exported
    assert reps[0].state == "drained"
    router.shutdown()
