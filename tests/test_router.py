"""Multi-engine serving tier tests (docs/scale-out.md): the
prefix-affinity router over replicated continuous engines.

Layers of evidence:

- host-level digest semantics (``prefix_digest``/``digest_match_len``)
  with no model — milliseconds;
- router-level routing proofs on the tiny model: outputs bit-exact vs
  dense per-request goldens through the replica fleet, affinity
  landing repeats on the cached replica, shed-aware skipping,
  graceful drain;
- the chaos layer (ISSUE-6 acceptance): a replica killed through the
  ``replica.run`` fault seam has every routed request re-routed and
  finished with a clean status, surviving replicas' outputs bit-exact,
  all engine/pool audits clean — and the no-survivor case fails with
  a structured status instead of hanging or dropping.
"""

import jax
import numpy as np
import pytest

from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.paged_kv_cache import PagePool
from triton_distributed_tpu.models.prefix_cache import (
    PrefixCache,
    digest_match_len,
)
from triton_distributed_tpu.runtime import mesh as mesh_mod


@pytest.fixture(scope="module")
def tier_model():
    """ONE tiny model (and mesh) for the whole module: engines are
    cheap but compiled programs cache per model instance, and every
    test here uses the same shapes — per-test models would recompile
    identical programs in a wall-clock-bound suite."""
    ctx = mesh_mod.initialize_distributed(tp=4, devices=jax.devices()[:4])
    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    yield model
    mesh_mod.finalize_distributed()


def make_router(model, n=2, **kw):
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.serving.router import Router

    engines = [
        ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64,
            prefix_cache=True,
        )
        for _ in range(n)
    ]
    return Router(engines, **kw)


def goldens(model, prompts, gens):
    eng = Engine(model, temperature=0.0)
    return [
        np.asarray(eng.serve(p[None], gen_len=g)[0, len(p):])
        for p, g in zip(prompts, gens)
    ]


PROMPTS = [
    np.asarray([5, 9, 2, 4], np.int32),
    np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32),
    np.asarray([11, 12, 13, 14], np.int32),
]
GENS = [4, 3, 4]


# -- host-level digest semantics (no model) -----------------------------


def test_prefix_digest_and_match_len():
    pool = PagePool(17)
    pool.free = [p for p in pool.free if p != 0]
    pc = PrefixCache(pool, 4)
    toks = list(range(100, 110))  # 2 full pages + a 2-token tail
    pc.insert_chain(pc.root, toks, pool.allocate(3))

    digest = pc.prefix_digest()
    # Exact chain: full match counts every cached token.
    assert digest_match_len(digest, toks) == 10
    # Longer prompt: only the cached prefix counts.
    assert digest_match_len(digest, toks + [1, 2, 3]) == 10
    # Divergence inside the partial tail counts the matched positions.
    assert digest_match_len(digest, toks[:9] + [999]) == 9
    # Divergence inside a full page stops without descending.
    assert digest_match_len(digest, toks[:2] + [999, 999]) == 2
    # Cold prompt / empty digest.
    assert digest_match_len(digest, [999, 998]) == 0
    assert digest_match_len([], toks) == 0
    assert digest_match_len(None, toks) == 0

    # The digest is a SNAPSHOT: evicting the tree doesn't mutate it.
    pc.flush()
    assert pc.node_count == 0
    assert pc.prefix_digest() == []
    assert digest_match_len(digest, toks) == 10


# -- routing over the tiny model ----------------------------------------


def test_router_outputs_match_goldens(tier_model):
    """Mixed requests through a 2-replica fleet: every output bit-exact
    vs the dense per-request goldens, results in submission order,
    audits clean, fleet stats aggregated cumulatively."""
    model = tier_model
    golds = goldens(model, PROMPTS, GENS)
    router = make_router(model, 2)
    try:
        results = router.run(list(zip(PROMPTS, GENS)), results=True)
        for r, gold in zip(results, golds):
            assert r.status == "ok"
            np.testing.assert_array_equal(r.tokens, gold)
        st = router.last_stats
        assert st["generated_tokens"] == sum(GENS)
        assert st["router"]["routed"] == 3
        assert st["router"]["healthy_replicas"] == 2
        assert router.audit() == []

        # Legacy (results=False) interface returns arrays in order.
        outs = router.run(list(zip(PROMPTS, GENS)))
        for got, gold in zip(outs, golds):
            np.testing.assert_array_equal(got, gold)
    finally:
        router.shutdown()


def test_router_affinity_lands_on_cached_replica(tier_model):
    """A repeated prompt routes to the replica whose radix tree cached
    it (the router-side digest mirror), not round-robin: the seeded
    replica serves every repeat and the engine-level prefix counters
    prove pages were actually reused."""
    model = tier_model
    p = np.asarray(list(range(40, 72)), np.int32)  # 2 full pages
    router = make_router(model, 2)
    try:
        router.run([(p, 2)], results=True)
        assert sum(r.runs for r in router.replicas) == 1
        seeded = next(r for r in router.replicas if r.runs == 1)
        assert seeded.match_len(p) >= 16  # mirror sees the population

        for _ in range(2):
            res = router.run([(p, 2)], results=True)
            assert res[0].status == "ok"
        st = router.last_stats["router"]
        assert st["affinity_hits"] == 2
        assert st["affinity_hit_tokens"] >= 32
        assert seeded.runs == 3  # every repeat landed on the cache
        assert seeded.totals["prefix_hit_tokens"] > 0
    finally:
        router.shutdown()


def test_router_shed_aware_skips_overloaded(tier_model):
    """A replica at its pending bound is skipped BEFORE the request
    bounces: with r0 saturated every request lands on r1; with both
    saturated the router still queues (least-loaded) instead of
    dropping."""
    model = tier_model
    router = make_router(model, 2)
    try:
        r0, r1 = router.replicas
        r0.max_pending = 0  # permanently "overloaded" for routing
        results = router.run(list(zip(PROMPTS, GENS)), results=True)
        assert all(r.status == "ok" for r in results)
        assert r0.runs == 0 and r1.served == 3
        assert router.last_stats["router"]["shed_skips"] >= 3

        r1.max_pending = 0  # everything saturated: queue, don't drop
        res = router.run([(PROMPTS[0], 2)], results=True)
        assert res[0].status == "ok"
    finally:
        router.shutdown()


def test_router_drain_replica(tier_model):
    """Graceful drain: the drained replica finishes its work, flushes
    its radix pages back to the pool, refuses new tickets, and the
    fleet keeps serving on the survivor."""
    model = tier_model
    router = make_router(model, 2)
    try:
        router.run(list(zip(PROMPTS, GENS)), results=True)
        name = router.replicas[0].name
        assert router.drain_replica(name)
        r0 = router.replica(name)
        assert r0.state == "drained"
        assert r0.engine.prefix.node_count == 0  # tree flushed
        assert len(r0.engine.pool.free) == r0.engine._capacity
        from triton_distributed_tpu.serving.replica import Ticket

        assert not r0.submit(Ticket(PROMPTS[0], 1))
        res = router.run([(PROMPTS[0], 2)], results=True)
        assert res[0].status == "ok"
        assert router.last_stats["router"]["healthy_replicas"] == 1
        assert router.audit() == []
    finally:
        router.shutdown()


# -- chaos: replica kill / hang / no survivors --------------------------


def test_router_replica_kill_reroutes_bit_exact(tier_model, fresh_telemetry):
    """ISSUE-6 acceptance: every request routed to a killed replica is
    re-routed and finishes ok; outputs (survivors AND re-routed) are
    bit-exact vs the dense goldens; the dead replica's engine audits
    clean (its run() teardown released everything)."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.runtime.faults import FaultPlan

    model = tier_model
    golds = goldens(model, PROMPTS, GENS)
    router = make_router(model, 2)
    try:
        plan = FaultPlan(seed=7).kill_replica(replica="r0")
        with plan:
            results = router.run(list(zip(PROMPTS, GENS)), results=True)
        assert plan.fired and plan.fired[0][0] == "replica.run"
        for r, gold in zip(results, golds):
            assert r.status == "ok", (r.status, r.reason)
            np.testing.assert_array_equal(r.tokens, gold)
        st = router.last_stats["router"]
        assert st["reroutes"] >= 1
        assert router.replica("r0").state == "dead"
        assert router.replica("r1").state == "healthy"
        assert router.audit() == []  # dead engine released everything
        kinds = [e.kind for e in obs_events.default_ring().tail(0)[0]]
        assert "replica_dead" in kinds and "reroute" in kinds
        assert "fault" in kinds  # the injection itself is in the ring

        # The fleet keeps serving on the survivor after the kill.
        res = router.run([(PROMPTS[0], GENS[0])], results=True)
        assert res[0].status == "ok"
        np.testing.assert_array_equal(res[0].tokens, golds[0])
    finally:
        router.shutdown()


def test_router_kill_without_survivors_fails_clean(tier_model):
    """No healthy replica left: requests fail with a structured PR 3
    status (never dropped, never hung), and the re-route ledger shows
    the attempts."""
    from triton_distributed_tpu.runtime.faults import FaultPlan

    model = tier_model
    router = make_router(model, 1)
    try:
        with FaultPlan(seed=3).kill_replica(replica="r0"):
            results = router.run([(PROMPTS[0], 2)], results=True)
        assert results[0].status == "failed"
        assert "routing failed" in results[0].reason
        assert len(results[0].tokens) == 0
        assert router.last_stats["router"]["failed_no_replica"] == 1
        assert router.audit() == []
    finally:
        router.shutdown()


def test_router_timeout_marks_replica_and_reroutes(tier_model):
    """Router-observed timeout (the hang arm of the seam): a replica
    stalled past ``request_timeout_s`` is taken out of rotation and
    the ticket retries on a survivor; the late run's results latch
    harmlessly."""
    from triton_distributed_tpu.runtime.faults import FaultPlan

    model = tier_model
    golds = goldens(model, [PROMPTS[0]], [2])
    router = make_router(model, 2)
    try:
        # Warm the decode/prefill programs (jit cache lives on the
        # model, shared by both replicas) BEFORE arming the timeout:
        # a cold compile must not read as a hung replica.
        router.run([(PROMPTS[0], 2)], results=True)
        router.request_timeout_s = 1.0
        plan = FaultPlan(seed=5).hang_replica(3.0, replica="r0")
        with plan:
            results = router.run([(PROMPTS[0], 2)], results=True)
            assert results[0].status == "ok"
            np.testing.assert_array_equal(results[0].tokens, golds[0])
            dead = [r for r in router.replicas if r.state == "dead"]
            assert len(dead) == 1 and "timeout" in dead[0].last_error
            assert router.last_stats["router"]["reroutes"] >= 1
            # Wait out the hung worker INSIDE the plan scope: it wakes,
            # runs its batch late (results latch-ignored), and exits.
            dead[0].join(timeout=30)
    finally:
        router.shutdown()
    assert router.audit() == []


def test_router_results_false_raises_on_failures(tier_model):
    """The legacy interface keeps the engine contract: failures raise
    RequestFailedError with per-request statuses attached."""
    from triton_distributed_tpu.models.continuous import (
        RequestFailedError,
    )
    from triton_distributed_tpu.runtime.faults import FaultPlan

    model = tier_model
    router = make_router(model, 1)
    try:
        with FaultPlan(seed=2).kill_replica(replica="r0"):
            with pytest.raises(RequestFailedError, match="failed"):
                router.run([(PROMPTS[0], 2)])
    finally:
        router.shutdown()


@pytest.mark.slow
def test_router_mega_int8_fleet_bit_exact(tier_model):
    """PR 7 compose: a fleet of ``mode="mega"`` int8 replicas behind
    the Router serves bit-exact vs per-request unfused int8 goldens,
    with fused launches actually happening on the replicas (the fast
    path survives the serving tier's threading and re-dispatch)."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.serving.router import Router

    model = tier_model

    def engine(mode):
        return ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64, mode=mode,
            kv_dtype="int8", prefix_cache=True,
        )

    # Disjoint prompts: no cross-request prefix reuse, so per-request
    # fresh-engine goldens hold regardless of where the router lands
    # each request.
    golds = [
        engine("xla").run([(p, g)])[0] for p, g in zip(PROMPTS, GENS)
    ]
    replicas = [engine("mega") for _ in range(2)]
    router = Router(replicas)
    try:
        results = router.run(list(zip(PROMPTS, GENS)), results=True)
        for r, gold in zip(results, golds):
            assert r.status == "ok"
            np.testing.assert_array_equal(r.tokens, gold)
        assert sum(e.stats["mega_launches"] for e in replicas) > 0
        assert router.audit() == []
    finally:
        router.shutdown()


# -- through the wire ----------------------------------------------------


def test_router_through_server(tier_model):
    """ModelServer(Router(...)): the wire protocol is unchanged, the
    stats payload carries the router ledger, drain_grace_s is
    surfaced, and the metrics verb scrapes the tdt_router_* series."""
    from triton_distributed_tpu.serving import ModelServer, request

    model = tier_model
    golds = goldens(model, PROMPTS[:2], GENS[:2])
    router = make_router(model, 2, drain_grace_s=1.5)
    server = ModelServer(router, drain_grace_s=1.5).start()
    try:
        resp = request(
            server.host, server.port,
            {"requests": [p.tolist() for p in PROMPTS[:2]],
             "gen_lens": GENS[:2]},
        )
        assert [r["status"] for r in resp["results"]] == ["ok", "ok"]
        for out, gold in zip(resp["outputs"], golds):
            np.testing.assert_array_equal(np.asarray(out, np.int32), gold)
        assert resp["stats"]["router"]["routed"] >= 2

        stats = request(server.host, server.port, {"cmd": "stats"})
        assert stats["stats"]["server"]["drain_grace_s"] == 1.5
        assert "replicas" in stats["stats"]["router"]

        m = request(server.host, server.port, {"cmd": "metrics"})
        assert "tdt_router_requests_total" in m["prometheus"]
    finally:
        server.shutdown()  # drains the router's replicas too
    assert all(r.state != "healthy" for r in router.replicas)
    assert router.audit() == []


def test_router_server_concurrent_payloads(tier_model):
    """A Router-backed server dispatches generation payloads WITHOUT
    the engine lock (concurrent_safe): two payloads from two
    connections complete concurrently across the fleet."""
    import threading

    from triton_distributed_tpu.serving import ModelServer, request

    model = tier_model
    router = make_router(model, 2)
    server = ModelServer(router).start()
    try:
        done = {}

        def gen(i, p, g):
            done[i] = request(
                server.host, server.port,
                {"requests": [p.tolist()], "gen_lens": [g]}, timeout=120,
            )

        threads = [
            threading.Thread(target=gen, args=(i, PROMPTS[i], GENS[i]),
                             daemon=True)
            for i in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        golds = goldens(model, PROMPTS[:2], GENS[:2])
        for i in range(2):
            assert done[i]["results"][0]["status"] == "ok"
            np.testing.assert_array_equal(
                np.asarray(done[i]["outputs"][0], np.int32), golds[i]
            )
    finally:
        server.shutdown()


def test_replace_add_replica_under_concurrent_submissions(
        fresh_telemetry):
    """ISSUE-10 satellite: ``replace_replica``/``add_replica`` while
    submissions are in flight — generation-suffixed names stay unique,
    retired replicas keep resolving (late hop judgments), and the
    fleet's cumulative totals count every delivered token exactly once
    (no double-counting across the swap)."""
    import threading as _threading

    from triton_distributed_tpu.models.stub import (
        StubEngine,
        stub_generate,
    )
    from triton_distributed_tpu.serving.replica import EngineReplica
    from triton_distributed_tpu.serving.router import Router

    def stub_replica(name):
        return EngineReplica(
            StubEngine(num_pages=64, page_size=4), name=name,
        )

    # r0's engine blocks on a test-controlled gate: its in-flight batch
    # provably CANNOT latch before the swap's re-route claims run, so
    # the exactly-once totals check below is deterministic — and the
    # late batch still completes inside the test (latch-losing,
    # excluded from totals by the DEAD accounting rule).
    gate = _threading.Event()

    class GatedStub(StubEngine):
        def run(self, reqs, *, results=False):
            gate.wait(30)
            return super().run(reqs, results=results)

    r0 = EngineReplica(GatedStub(num_pages=64, page_size=4), name="r0")
    router = Router([r0, stub_replica("r1")], max_reroutes=3)
    prompts = [np.arange(i + 1, i + 7, dtype=np.int32) for i in range(6)]
    gens = [5 + (i % 3) for i in range(6)]
    golds = [stub_generate(p, g) for p, g in zip(prompts, gens)]
    results = {}
    barrier = _threading.Barrier(len(prompts) + 1)

    def submit(i):
        barrier.wait()
        results[i] = router.run([(prompts[i], gens[i])], results=True)[0]

    threads = [
        _threading.Thread(target=submit, args=(i,), daemon=True)
        for i in range(len(prompts))
    ]
    for t in threads:
        t.start()
    barrier.wait()
    # Mid-flight: kill r0 (its orphans re-route), swap in its
    # generation-suffixed successor, and grow the rotation.
    dead = router.replica("r0")
    orphans = dead.mark_unhealthy("operator kill for swap test")
    router._on_replica_failure(dead, orphans)
    retired = router.replace_replica("r0", stub_replica("r0#1"))
    assert retired is dead
    router.add_replica(stub_replica("r2"))
    with pytest.raises(ValueError, match="already live"):
        router.add_replica(stub_replica("r0#1"))
    for t in threads:
        t.join(timeout=60)
    # Every submission delivered, bit-exact.
    assert sorted(results) == list(range(len(prompts)))
    for i, r in results.items():
        assert r.status == "ok", (i, r.status, r.reason)
        assert r.tokens.tolist() == golds[i]
    # Names stay unique across live + retired.
    names = [r.name for r in router.replicas]
    assert sorted(names) == sorted(set(names))
    assert "r0#1" in names and "r2" in names
    # The retired replica keeps resolving (late hop stamps need it).
    assert router.replica("r0") is dead
    assert router.last_stats["router"]["retired_replicas"] == 1
    # Release the dead replica's wedged batch: it latch-loses and the
    # DEAD rule keeps it out of the ledger.
    gate.set()
    dead.join(timeout=30)
    assert dead.runs == 0 and dead.totals["generated_tokens"] == 0
    # Fleet totals count each delivered token exactly once: re-routed
    # work counts where it actually ran, the duplicate late batch is
    # excluded.
    delivered = sum(len(r.tokens) for r in results.values())
    assert router.last_stats["generated_tokens"] == delivered
    router.shutdown()
