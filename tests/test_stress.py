"""Stress + race-provocation tests for the overlap kernels.

Parity: reference ``test/stress/stress_test_ag_gemm.py`` (randomized
iteration loop with straggler injection, :54-81) and the
``for_correctness`` fixtures (``allgather_gemm.py:507-508``). The
interpret-mode simulator executes DMAs and semaphores with faithful
ordering, so a missing wait surfaces as wrong output here, cluster-free.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import all_reduce_op
from triton_distributed_tpu.ops.collectives.all_reduce import AllReduceMethod
from triton_distributed_tpu.ops.overlap.ag_gemm import AGGemmConfig, ag_gemm_op


def _gold_ag_gemm(a, b):
    return np.asarray(a) @ np.asarray(b)


class TestAgGemmStress:
    @pytest.mark.parametrize("straggler", [None, 0, 2])
    def test_straggler_ranks(self, ctx4, rng, straggler):
        m, k, n_cols = 16, 64, 256
        cfg = AGGemmConfig(
            tile_n=128, straggler_rank=straggler, straggler_nanos=200_000
        )
        a = jnp.asarray(rng.standard_normal((m * 4, k), dtype=np.float32))
        b = jnp.asarray(rng.standard_normal((k, n_cols), dtype=np.float32))
        out = ag_gemm_op(a, b, "tp", cfg, ctx4)
        np.testing.assert_allclose(
            np.asarray(out), _gold_ag_gemm(a, b), rtol=2e-4, atol=2e-4
        )

    def test_for_correctness_iterations(self, ctx4, rng):
        """Randomized loop with producer delays (parity: the 100-iter
        stress script; trimmed for the 1-core CI simulator)."""
        m, k, n_cols = 8, 64, 128
        cfg = AGGemmConfig(tile_n=128, for_correctness=True)
        for _ in range(10):
            a = jnp.asarray(rng.standard_normal((m * 4, k), dtype=np.float32))
            b = jnp.asarray(
                rng.standard_normal((k, n_cols), dtype=np.float32)
            )
            out = ag_gemm_op(a, b, "tp", cfg, ctx4)
            got = np.asarray(out)
            assert not np.isnan(got).any()
            np.testing.assert_allclose(
                got, _gold_ag_gemm(a, b), rtol=2e-4, atol=2e-4
            )


class TestAllReduceStress:
    def test_one_shot_with_straggler(self, ctx4, rng):
        from jax.sharding import PartitionSpec as P
        from triton_distributed_tpu.ops.collectives.all_reduce import all_reduce

        x = jnp.asarray(rng.standard_normal((4, 16, 128), dtype=np.float32))

        def body(xi):
            return all_reduce(
                xi[0], "tp", AllReduceMethod.ONE_SHOT, ctx4,
                straggler_rank=1, straggler_nanos=200_000,
            )

        f = ctx4.shard_map(
            body, in_specs=P("tp", None, None), out_specs=P(None, None)
        )
        np.testing.assert_allclose(
            np.asarray(f(x)), np.asarray(x).sum(0), rtol=1e-5, atol=1e-5
        )


def test_multi_step_exchange_with_straggler(ctx4):
    """The multi-step LM-head cross-rank argmax under a lagged rank
    (race-provocation parity: reference for_correctness/straggler
    fixtures): the exchange's wait/barrier discipline must keep tokens
    exact even when one rank's candidate push is late."""
    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.models import AutoLLM

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    B, NS = 2, 3
    cache = model.new_cache(B, max_length=64)
    step_gold = model.decode_fn("xla")
    _, cache = step_gold(model.params, jnp.asarray([3, 5], jnp.int32), cache)

    mega = MegaQwen3(model)
    s_max = int(cache.k.shape[3])
    tok0 = jnp.asarray([19, 23], jnp.int32)

    # Gold: the single-step mega chain (same kernel math, no exchange —
    # argmax runs on the host), so a consistently-wrong exchange can't
    # agree with it by construction.
    step = mega.decode_fn(B, s_max)
    t, c = tok0, jax.tree.map(jnp.copy, cache)
    gold = []
    for _ in range(NS):
        lg, c = step(model.params, t, c)
        t = jnp.argmax(lg, -1).astype(jnp.int32)
        gold.append(np.asarray(t))

    clean = mega.build_multi(B, s_max, NS)
    lagged = mega.build_multi(B, s_max, NS, straggler_rank=2)
    t_clean, _, _ = clean(model.params, tok0, jax.tree.map(jnp.copy, cache))
    t_lag, _, _ = lagged(model.params, tok0, jax.tree.map(jnp.copy, cache))
    np.testing.assert_array_equal(np.asarray(t_clean), np.stack(gold))
    np.testing.assert_array_equal(np.asarray(t_lag), np.stack(gold))
