"""Stress + race-provocation tests for the overlap kernels.

Parity: reference ``test/stress/stress_test_ag_gemm.py`` (randomized
iteration loop with straggler injection, :54-81) and the
``for_correctness`` fixtures (``allgather_gemm.py:507-508``). The
interpret-mode simulator executes DMAs and semaphores with faithful
ordering, so a missing wait surfaces as wrong output here, cluster-free.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import all_reduce_op
from triton_distributed_tpu.ops.collectives.all_reduce import AllReduceMethod
from triton_distributed_tpu.ops.overlap.ag_gemm import AGGemmConfig, ag_gemm_op


def _gold_ag_gemm(a, b):
    return np.asarray(a) @ np.asarray(b)


class TestAgGemmStress:
    @pytest.mark.parametrize("straggler", [None, 0, 2])
    def test_straggler_ranks(self, ctx4, rng, straggler):
        m, k, n_cols = 16, 64, 256
        cfg = AGGemmConfig(
            tile_n=128, straggler_rank=straggler, straggler_nanos=200_000
        )
        a = jnp.asarray(rng.standard_normal((m * 4, k), dtype=np.float32))
        b = jnp.asarray(rng.standard_normal((k, n_cols), dtype=np.float32))
        out = ag_gemm_op(a, b, "tp", cfg, ctx4)
        np.testing.assert_allclose(
            np.asarray(out), _gold_ag_gemm(a, b), rtol=2e-4, atol=2e-4
        )

    @pytest.mark.slow
    def test_for_correctness_iterations(self, ctx4, rng):
        """Randomized loop with producer delays (parity: the 100-iter
        stress script; trimmed for the 1-core CI simulator)."""
        m, k, n_cols = 8, 64, 128
        cfg = AGGemmConfig(tile_n=128, for_correctness=True)
        for _ in range(10):
            a = jnp.asarray(rng.standard_normal((m * 4, k), dtype=np.float32))
            b = jnp.asarray(
                rng.standard_normal((k, n_cols), dtype=np.float32)
            )
            out = ag_gemm_op(a, b, "tp", cfg, ctx4)
            got = np.asarray(out)
            assert not np.isnan(got).any()
            np.testing.assert_allclose(
                got, _gold_ag_gemm(a, b), rtol=2e-4, atol=2e-4
            )


class TestAllReduceStress:
    def test_one_shot_with_straggler(self, ctx4, rng):
        from jax.sharding import PartitionSpec as P
        from triton_distributed_tpu.ops.collectives.all_reduce import all_reduce

        x = jnp.asarray(rng.standard_normal((4, 16, 128), dtype=np.float32))

        def body(xi):
            return all_reduce(
                xi[0], "tp", AllReduceMethod.ONE_SHOT, ctx4,
                straggler_rank=1, straggler_nanos=200_000,
            )

        f = ctx4.shard_map(
            body, in_specs=P("tp", None, None), out_specs=P(None, None)
        )
        np.testing.assert_allclose(
            np.asarray(f(x)), np.asarray(x).sum(0), rtol=1e-5, atol=1e-5
        )


@pytest.mark.slow
def test_multi_step_exchange_with_straggler(ctx4):
    """The multi-step LM-head cross-rank argmax under a lagged rank
    (race-provocation parity: reference for_correctness/straggler
    fixtures): the exchange's wait/barrier discipline must keep tokens
    exact even when one rank's candidate push is late."""
    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.models import AutoLLM

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    B, NS = 2, 3
    cache = model.new_cache(B, max_length=64)
    step_gold = model.decode_fn("xla")
    _, cache = step_gold(model.params, jnp.asarray([3, 5], jnp.int32), cache)

    mega = MegaQwen3(model)
    s_max = int(cache.k.shape[3])
    tok0 = jnp.asarray([19, 23], jnp.int32)

    # Gold: the single-step mega chain (same kernel math, no exchange —
    # argmax runs on the host), so a consistently-wrong exchange can't
    # agree with it by construction.
    step = mega.decode_fn(B, s_max)
    t, c = tok0, jax.tree.map(jnp.copy, cache)
    gold = []
    for _ in range(NS):
        lg, c = step(model.params, t, c)
        t = jnp.argmax(lg, -1).astype(jnp.int32)
        gold.append(np.asarray(t))

    clean = mega.build_multi(B, s_max, NS)
    lagged = mega.build_multi(B, s_max, NS, straggler_rank=2)
    t_clean, _, _ = clean(model.params, tok0, jax.tree.map(jnp.copy, cache))
    t_lag, _, _ = lagged(model.params, tok0, jax.tree.map(jnp.copy, cache))
    np.testing.assert_array_equal(np.asarray(t_clean), np.stack(gold))
    np.testing.assert_array_equal(np.asarray(t_lag), np.stack(gold))


# -- reference-scale randomized sweep with hang detection -------------------
#
# Parity: ``test/stress/stress_test_ag_gemm.py:54-81`` — 100 randomized
# iterations with stragglers — plus the launcher's ``--verify_hang``
# role: each iteration runs under a watchdog so a deadlocked semaphore
# protocol fails the test with a HANG verdict instead of wedging the
# suite. (Interpret-mode analog: the thread can't be killed, but the
# suite reports and moves on — the reference kills the process group.)

_HANG_TIMEOUT_S = 180


def _run_guarded(fn, label):
    import threading

    result: list = []
    error: list = []

    def target():
        try:
            result.append(fn())
        except BaseException as e:  # noqa: BLE001 — re-raised below
            error.append(e)

    th = threading.Thread(target=target, daemon=True)
    th.start()
    th.join(_HANG_TIMEOUT_S)
    if th.is_alive():
        pytest.fail(
            f"HANG: {label} still running after {_HANG_TIMEOUT_S}s "
            "(interpret-mode --verify_hang analog)"
        )
    if error:
        raise error[0]
    return result[0]


@pytest.mark.slow
class TestRandomizedSweep:
    """~100 randomized iterations across the four overlap/comm families.
    Every iteration is seeded by its index — a failure message names the
    op + seed, reproducible as a one-liner."""

    N_ITERS = 25

    def test_ag_gemm_randomized(self, ctx4):
        for it in range(self.N_ITERS):
            rng = np.random.default_rng(1000 + it)
            m_per = int(rng.choice([8, 16, 32]))
            k = int(rng.choice([64, 128]))
            n_cols = int(rng.choice([128, 256]))
            straggler = rng.choice([None, 0, 1, 2, 3])
            cfg = AGGemmConfig(
                tile_n=128,
                straggler_rank=None if straggler is None else int(straggler),
                straggler_nanos=int(rng.integers(50_000, 400_000)),
                for_correctness=bool(rng.integers(0, 2)),
            )
            a = jnp.asarray(rng.standard_normal((m_per * 4, k)), jnp.float32)
            b = jnp.asarray(rng.standard_normal((k, n_cols)), jnp.float32)
            out = _run_guarded(
                lambda: np.asarray(ag_gemm_op(a, b, "tp", cfg, ctx4)),
                f"ag_gemm seed={1000 + it}",
            )
            assert not np.isnan(out).any(), f"seed={1000 + it}"
            np.testing.assert_allclose(
                out, _gold_ag_gemm(a, b), rtol=2e-4, atol=2e-4,
                err_msg=f"seed={1000 + it}",
            )

    def test_gemm_rs_randomized(self, ctx4):
        from triton_distributed_tpu.ops.overlap.gemm_rs import (
            GemmRSConfig,
            gemm_rs_op,
        )

        for it in range(self.N_ITERS):
            rng = np.random.default_rng(2000 + it)
            m_per = int(rng.choice([8, 16, 32]))
            k = int(rng.choice([64, 128]))
            n_cols = int(rng.choice([128, 256]))
            tile_m = int(rng.choice([4, 8, m_per]))
            cfg = GemmRSConfig(
                tile_n=128,
                tile_m=tile_m,
                bidir=bool(rng.integers(0, 2)),
            )
            a = jnp.asarray(rng.standard_normal((m_per * 4, k)), jnp.float32)
            b = jnp.asarray(rng.standard_normal((k, n_cols)), jnp.float32)
            out = _run_guarded(
                lambda: np.asarray(gemm_rs_op(a, b, "tp", cfg, ctx4)),
                f"gemm_rs seed={2000 + it}",
            )
            assert not np.isnan(out).any(), f"seed={2000 + it}"
            np.testing.assert_allclose(
                out, np.asarray(a) @ np.asarray(b), rtol=2e-4, atol=2e-4,
                err_msg=f"seed={2000 + it}",
            )

    def test_allreduce_randomized(self, ctx4):
        from jax.sharding import PartitionSpec as P
        from triton_distributed_tpu.ops.collectives.all_reduce import all_reduce

        methods = [
            AllReduceMethod.ONE_SHOT,
            AllReduceMethod.TWO_SHOT,
            AllReduceMethod.DOUBLING,
            AllReduceMethod.XLA,
        ]
        for it in range(self.N_ITERS):
            rng = np.random.default_rng(3000 + it)
            rows = int(rng.choice([8, 16, 32]))
            method = methods[int(rng.integers(0, len(methods)))]
            straggler = rng.choice([None, 0, 1, 2, 3])
            x = jnp.asarray(
                rng.standard_normal((4, rows, 128)), jnp.float32
            )

            def body(xi, method=method, straggler=straggler):
                kwargs = {}
                if method != AllReduceMethod.XLA and straggler is not None:
                    kwargs = dict(
                        straggler_rank=int(straggler),
                        straggler_nanos=200_000,
                    )
                return all_reduce(xi[0], "tp", method, ctx4, **kwargs)

            f = ctx4.shard_map(
                body, in_specs=P("tp", None, None), out_specs=P(None, None)
            )
            out = _run_guarded(
                lambda: np.asarray(f(x)),
                f"allreduce {method.value} seed={3000 + it}",
            )
            np.testing.assert_allclose(
                out, np.asarray(x).sum(0), rtol=1e-4, atol=1e-4,
                err_msg=f"seed={3000 + it}",
            )

    def test_ep_a2a_randomized(self, ctx4):
        import functools

        from jax.sharding import PartitionSpec as P
        from triton_distributed_tpu.ops.moe import ep_moe_ffn

        for it in range(self.N_ITERS):
            rng = np.random.default_rng(4000 + it)
            t_loc = int(rng.choice([4, 8, 16]))
            d, fdim, e, kk = 32, 16, 8, 2
            payload = rng.choice([None, "fp8"])
            method = ["xla", "pallas"][int(rng.integers(0, 2))]
            skew = float(rng.choice([0.0, 5.0, 50.0]))
            x = jnp.asarray(
                np.abs(rng.standard_normal((4 * t_loc, d))) * 0.1, jnp.float32
            )
            w_r = jnp.asarray(
                rng.standard_normal((d, e)) * 0.1, jnp.float32
            ).at[:, :2].add(skew)
            w1 = jnp.asarray(
                rng.standard_normal((e, d, 2 * fdim)) * 0.1, jnp.float32
            )
            w2 = jnp.asarray(
                rng.standard_normal((e, fdim, d)) * 0.1, jnp.float32
            )
            f = ctx4.shard_map(
                functools.partial(
                    ep_moe_ffn, k=kk, axis="tp", method=method,
                    payload_dtype=None if payload is None else str(payload),
                    ctx=ctx4,
                ),
                in_specs=(P("tp", None), P(), P("tp", None, None),
                          P("tp", None, None)),
                out_specs=P("tp", None),
            )
            gold_f = ctx4.shard_map(
                functools.partial(ep_moe_ffn, k=kk, axis="tp", method="xla",
                                  ctx=ctx4),
                in_specs=(P("tp", None), P(), P("tp", None, None),
                          P("tp", None, None)),
                out_specs=P("tp", None),
            )
            out = _run_guarded(
                lambda: np.asarray(f(x, w_r, w1, w2)),
                f"ep_a2a {method}/{payload} seed={4000 + it}",
            )
            gold = np.asarray(gold_f(x, w_r, w1, w2))
            assert not np.isnan(out).any(), f"seed={4000 + it}"
            tol = 5e-2 if payload == "fp8" else 1e-5
            np.testing.assert_allclose(
                out, gold, rtol=tol, atol=tol, err_msg=f"seed={4000 + it}"
            )

    def test_multi_step_exchange_randomized_stragglers(self, ctx4):
        """The promoted multi-step argmax race fixture (VERDICT r3 task
        7): random straggler rank/teammate each round, tokens must stay
        exact."""
        from triton_distributed_tpu.megakernel import MegaQwen3
        from triton_distributed_tpu.models import AutoLLM

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        B, NS = 2, 2
        cache = model.new_cache(B, max_length=64)
        step_gold = model.decode_fn("xla")
        _, cache = step_gold(
            model.params, jnp.asarray([3, 5], jnp.int32), cache
        )
        mega = MegaQwen3(model)
        s_max = int(cache.k.shape[3])
        tok0 = jnp.asarray([19, 23], jnp.int32)

        step = mega.decode_fn(B, s_max)
        t, c = tok0, jax.tree.map(jnp.copy, cache)
        gold = []
        for _ in range(NS):
            lg, c = step(model.params, t, c)
            t = jnp.argmax(lg, -1).astype(jnp.int32)
            gold.append(np.asarray(t))

        for it in range(6):
            rng = np.random.default_rng(5000 + it)
            lagged = mega.build_multi(
                B, s_max, NS, straggler_rank=int(rng.integers(0, 4))
            )
            t_lag = _run_guarded(
                lambda: np.asarray(
                    lagged(model.params, tok0, jax.tree.map(jnp.copy, cache))[0]
                ),
                f"mega_multi straggler seed={5000 + it}",
            )
            np.testing.assert_array_equal(
                t_lag, np.stack(gold), err_msg=f"seed={5000 + it}"
            )
