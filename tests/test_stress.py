"""Stress + race-provocation tests for the overlap kernels.

Parity: reference ``test/stress/stress_test_ag_gemm.py`` (randomized
iteration loop with straggler injection, :54-81) and the
``for_correctness`` fixtures (``allgather_gemm.py:507-508``). The
interpret-mode simulator executes DMAs and semaphores with faithful
ordering, so a missing wait surfaces as wrong output here, cluster-free.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import all_reduce_op
from triton_distributed_tpu.ops.collectives.all_reduce import AllReduceMethod
from triton_distributed_tpu.ops.overlap.ag_gemm import AGGemmConfig, ag_gemm_op


def _gold_ag_gemm(a, b):
    return np.asarray(a) @ np.asarray(b)


class TestAgGemmStress:
    @pytest.mark.parametrize("straggler", [None, 0, 2])
    def test_straggler_ranks(self, ctx4, rng, straggler):
        m, k, n_cols = 16, 64, 256
        cfg = AGGemmConfig(
            tile_n=128, straggler_rank=straggler, straggler_nanos=200_000
        )
        a = jnp.asarray(rng.standard_normal((m * 4, k), dtype=np.float32))
        b = jnp.asarray(rng.standard_normal((k, n_cols), dtype=np.float32))
        out = ag_gemm_op(a, b, "tp", cfg, ctx4)
        np.testing.assert_allclose(
            np.asarray(out), _gold_ag_gemm(a, b), rtol=2e-4, atol=2e-4
        )

    def test_for_correctness_iterations(self, ctx4, rng):
        """Randomized loop with producer delays (parity: the 100-iter
        stress script; trimmed for the 1-core CI simulator)."""
        m, k, n_cols = 8, 64, 128
        cfg = AGGemmConfig(tile_n=128, for_correctness=True)
        for _ in range(10):
            a = jnp.asarray(rng.standard_normal((m * 4, k), dtype=np.float32))
            b = jnp.asarray(
                rng.standard_normal((k, n_cols), dtype=np.float32)
            )
            out = ag_gemm_op(a, b, "tp", cfg, ctx4)
            got = np.asarray(out)
            assert not np.isnan(got).any()
            np.testing.assert_allclose(
                got, _gold_ag_gemm(a, b), rtol=2e-4, atol=2e-4
            )


class TestAllReduceStress:
    def test_one_shot_with_straggler(self, ctx4, rng):
        from jax.sharding import PartitionSpec as P
        from triton_distributed_tpu.ops.collectives.all_reduce import all_reduce

        x = jnp.asarray(rng.standard_normal((4, 16, 128), dtype=np.float32))

        def body(xi):
            return all_reduce(
                xi[0], "tp", AllReduceMethod.ONE_SHOT, ctx4,
                straggler_rank=1, straggler_nanos=200_000,
            )

        f = ctx4.shard_map(
            body, in_specs=P("tp", None, None), out_specs=P(None, None)
        )
        np.testing.assert_allclose(
            np.asarray(f(x)), np.asarray(x).sum(0), rtol=1e-5, atol=1e-5
        )
