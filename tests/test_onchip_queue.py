"""Measurement-queue session runner: mid-window outage handling.

The queue (``perf/onchip_session.py``) runs each on-chip step in a
bounded subprocess. A relay that dies MID-window must abort the
session at the next step failure (after one cheap reprobe) rather than
grinding serially through every remaining step's timeout (~10 h for a
full queue) while the watcher — blocked on the session process —
cannot see the next window open."""

import importlib
import json
import os
import sys

import pytest


@pytest.fixture
def session(monkeypatch, tmp_path):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(root, "perf"))
    sys.path.insert(0, root)
    # Keep the chip lock private to the test BEFORE (re)loading
    # _tpulock: it reads TDT_TPU_LOCK at import time, and flocking the
    # real path could block behind a live watcher window for 15 min.
    monkeypatch.setenv("TDT_TPU_LOCK", str(tmp_path / "tpu.lock"))
    import _tpulock
    import onchip_session

    importlib.reload(_tpulock)
    importlib.reload(onchip_session)
    return onchip_session


def _fake_steps(marker_path):
    ok = f"open({marker_path!r}, 'a').write('x')"
    return [
        ("probe", [sys.executable, "-c", "pass"], 30),
        ("fails", [sys.executable, "-c", "import sys; sys.exit(1)"], 30),
        ("after", [sys.executable, "-c", ok], 30),
    ]


def test_dead_relay_aborts_instead_of_grinding(
    session, monkeypatch, tmp_path
):
    marker = tmp_path / "after_ran"
    monkeypatch.setattr(session, "STEPS", _fake_steps(str(marker)))
    # Reprobe sees a dead relay.
    monkeypatch.setattr(
        session, "_PROBE", "import sys; sys.exit(3)"
    )
    log = tmp_path / "log.jsonl"
    rc = session.main(["--log", str(log)])
    assert rc == 1
    assert not marker.exists(), "step after the outage must NOT run"
    recs = [json.loads(ln) for ln in log.read_text().splitlines()]
    steps = [r["step"] for r in recs]
    assert steps == ["probe", "fails", "reprobe"]
    assert recs[-1]["rc"] == 3


def test_live_relay_continues_past_step_local_failure(
    session, monkeypatch, tmp_path
):
    marker = tmp_path / "after_ran"
    monkeypatch.setattr(session, "STEPS", _fake_steps(str(marker)))
    # Reprobe answers: the failure was step-local, keep draining.
    monkeypatch.setattr(session, "_PROBE", "pass")
    log = tmp_path / "log.jsonl"
    rc = session.main(["--log", str(log)])
    assert rc == 2  # one step failed overall
    assert marker.exists(), "queue must continue after a live reprobe"
    recs = [json.loads(ln) for ln in log.read_text().splitlines()]
    assert [r["step"] for r in recs] == [
        "probe", "fails", "reprobe", "after"
    ]
    assert recs[2]["rc"] == 0
