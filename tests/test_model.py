"""Qwen3 model + engine tests (parity: reference test_e2e_inference.py /
test_tp_e2e.py — golden = an independent dense HF-semantics forward)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.models import AutoLLM, Engine, get_config
from triton_distributed_tpu.models.qwen import Qwen3, load_hf_state_dict


def _make_hf_state(cfg, rng):
    """Random HF-named state dict (torch [out, in] layout)."""
    d, hd = cfg.hidden_size, cfg.head_dim
    state = {
        "model.embed_tokens.weight": rng.standard_normal(
            (cfg.vocab_size, d)
        ).astype(np.float32) * 0.02,
        "model.norm.weight": np.ones(d, np.float32),
        "lm_head.weight": rng.standard_normal((cfg.vocab_size, d)).astype(
            np.float32
        ) * 0.02,
    }
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        sc = 0.05
        state[p + "self_attn.q_proj.weight"] = (
            rng.standard_normal((cfg.num_q_heads * hd, d)).astype(np.float32) * sc
        )
        state[p + "self_attn.k_proj.weight"] = (
            rng.standard_normal((cfg.num_kv_heads * hd, d)).astype(np.float32) * sc
        )
        state[p + "self_attn.v_proj.weight"] = (
            rng.standard_normal((cfg.num_kv_heads * hd, d)).astype(np.float32) * sc
        )
        state[p + "self_attn.o_proj.weight"] = (
            rng.standard_normal((d, cfg.num_q_heads * hd)).astype(np.float32) * sc
        )
        state[p + "self_attn.q_norm.weight"] = np.ones(hd, np.float32)
        state[p + "self_attn.k_norm.weight"] = (
            1.0 + 0.1 * rng.standard_normal(hd).astype(np.float32)
        )
        state[p + "input_layernorm.weight"] = np.ones(d, np.float32)
        state[p + "post_attention_layernorm.weight"] = np.ones(d, np.float32)
        state[p + "mlp.gate_proj.weight"] = (
            rng.standard_normal((cfg.intermediate_size, d)).astype(np.float32) * sc
        )
        state[p + "mlp.up_proj.weight"] = (
            rng.standard_normal((cfg.intermediate_size, d)).astype(np.float32) * sc
        )
        state[p + "mlp.down_proj.weight"] = (
            rng.standard_normal((d, cfg.intermediate_size)).astype(np.float32) * sc
        )
    return state


def _golden_forward(cfg, state, tokens):
    """Independent dense forward over the full sequence; returns logits
    [S, V] f32. Follows HF Qwen3 semantics (rmsnorm, qk-norm, rope,
    GQA causal attention, SwiGLU)."""

    def rms(x, w, eps=1e-6):
        return x * (1.0 / np.sqrt((x * x).mean(-1, keepdims=True) + eps)) * w

    def rope(x, pos, theta):
        hd = x.shape[-1]
        inv = 1.0 / theta ** (np.arange(0, hd, 2) / hd)
        ang = pos[:, None] * inv  # [S, hd/2]
        cos, sin = np.cos(ang), np.sin(ang)
        x1, x2 = x[..., : hd // 2], x[..., hd // 2 :]
        return np.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)

    d, hd = cfg.hidden_size, cfg.head_dim
    x = state["model.embed_tokens.weight"][tokens]  # [S, d]
    s = len(tokens)
    pos = np.arange(s, dtype=np.float64)
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}."
        h = rms(x, state[p + "input_layernorm.weight"])
        q = (h @ state[p + "self_attn.q_proj.weight"].T).reshape(
            s, cfg.num_q_heads, hd
        )
        k = (h @ state[p + "self_attn.k_proj.weight"].T).reshape(
            s, cfg.num_kv_heads, hd
        )
        v = (h @ state[p + "self_attn.v_proj.weight"].T).reshape(
            s, cfg.num_kv_heads, hd
        )
        q = rms(q, state[p + "self_attn.q_norm.weight"])
        k = rms(k, state[p + "self_attn.k_norm.weight"])
        q = rope(q.swapaxes(0, 1), pos, cfg.rope_theta)  # [hq, S, hd]
        k = rope(k.swapaxes(0, 1), pos, cfg.rope_theta)
        v = v.swapaxes(0, 1)
        g = cfg.num_q_heads // cfg.num_kv_heads
        k = np.repeat(k, g, axis=0)
        v = np.repeat(v, g, axis=0)
        sc = np.einsum("hqd,hkd->hqk", q, k) / np.sqrt(hd)
        mask = np.tril(np.ones((s, s), bool))
        sc = np.where(mask, sc, -1e30)
        pr = np.exp(sc - sc.max(-1, keepdims=True))
        pr /= pr.sum(-1, keepdims=True)
        o = np.einsum("hqk,hkd->hqd", pr, v)
        o = o.swapaxes(0, 1).reshape(s, cfg.num_q_heads * hd)
        x = x + o @ state[p + "self_attn.o_proj.weight"].T
        h = rms(x, state[p + "post_attention_layernorm.weight"])
        gate = h @ state[p + "mlp.gate_proj.weight"].T
        up = h @ state[p + "mlp.up_proj.weight"].T
        act = gate / (1.0 + np.exp(-gate)) * up
        x = x + act @ state[p + "mlp.down_proj.weight"].T
    x = rms(x, state["model.norm.weight"])
    return x @ state["lm_head.weight"].T


@pytest.fixture
def tiny_setup(ctx4, rng):
    cfg = get_config("tiny")
    state = _make_hf_state(cfg, rng)
    model = Qwen3(cfg, ctx=ctx4)
    model.set_params(load_hf_state_dict(cfg, state, ctx4.axis_size("tp")))
    return cfg, state, model


@pytest.mark.parametrize("mode", ["xla", "pallas"])
def test_prefill_matches_golden(tiny_setup, mode):
    cfg, state, model = tiny_setup
    tokens = np.arange(16, dtype=np.int32) % cfg.vocab_size
    cache = model.new_cache(1)
    logits, cache = model.prefill(jnp.asarray(tokens), cache, mode)
    gold = _golden_forward(cfg, state, tokens)[-1]
    np.testing.assert_allclose(np.asarray(logits), gold, atol=2e-3, rtol=2e-3)
    assert int(cache.kv_len[0]) == 16


def test_decode_matches_golden(tiny_setup):
    """Prefill 16 tokens then decode 3 more greedily; every step's logits
    must match the golden full-sequence forward."""
    cfg, state, model = tiny_setup
    tokens = list(np.arange(16, dtype=np.int32))
    cache = model.new_cache(1)
    logits, cache = model.prefill(jnp.asarray(np.asarray(tokens)), cache, "xla")
    for _ in range(3):
        gold = _golden_forward(cfg, state, np.asarray(tokens))[-1]
        np.testing.assert_allclose(
            np.asarray(logits), gold, atol=2e-3, rtol=2e-3
        )
        nxt = int(np.argmax(gold))
        logits_b, cache = model.decode_step(
            jnp.asarray([nxt], jnp.int32), cache, "xla"
        )
        logits = logits_b[0]
        tokens.append(nxt)


def test_engine_serve(ctx4):
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = Engine(model, temperature=0.0, mode="xla")
    prompt = np.arange(8, dtype=np.int32)[None].repeat(2, 0)  # [2, 8]
    out = eng.serve(prompt, gen_len=4)
    assert out.shape == (2, 12)
    # Same prompt rows → identical greedy continuations.
    np.testing.assert_array_equal(out[0], out[1])


def test_engine_prompt_padding_inert(ctx4):
    """Left-padded prompts with prompt_start generate the same
    continuation as the unpadded prompt (pads must not be attended)."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = Engine(model, temperature=0.0, mode="xla")
    real = np.arange(3, 11, dtype=np.int32)  # length 8 (tp-divisible)
    gold = eng.serve(real[None], gen_len=4)[0, 8:]
    # Same prompt left-padded by 4 junk tokens to length 12 (pad to 12).
    padded = np.concatenate([np.full(4, 77, np.int32), real])[None]
    out = eng.serve(padded, gen_len=4, prompt_start=[4])[0, 12:]
    np.testing.assert_array_equal(out, gold)
    # Sanity: WITHOUT prompt_start the junk perturbs generation.
    out_bad = eng.serve(padded, gen_len=4)[0, 12:]
    assert not np.array_equal(out_bad, gold)


class TestPagedKVCache:
    """Parity: reference mega_triton_kernel/models/paged_kv_cache.py —
    page-pool cache with free-list allocation and table indirection."""

    def test_append_and_dense_view(self, ctx4, rng):
        import jax.numpy as jnp
        from triton_distributed_tpu.models.config import get_config
        from triton_distributed_tpu.models.paged_kv_cache import (
            append,
            as_dense,
            init_paged_cache,
        )

        cfg = get_config("tiny")
        B = 2
        cache, pool = init_paged_cache(
            cfg, B, ctx4, max_length=64, page_size=16
        )
        L, hkv, hd = cfg.num_layers, cfg.num_kv_heads, cfg.head_dim

        gold_k = np.zeros((L, B, hkv, 64, hd), np.float32)
        for t in range(20):  # crosses a page boundary (page_size=16)
            k_new = jnp.asarray(
                rng.standard_normal((L, B, hkv, hd)), jnp.float32
            )
            v_new = jnp.asarray(
                rng.standard_normal((L, B, hkv, hd)), jnp.float32
            )
            gold_k[:, :, :, t] = np.asarray(k_new)
            cache = append(cache, k_new, v_new)

        k_dense, _ = as_dense(cache)
        np.testing.assert_allclose(
            np.asarray(k_dense)[:, :, :, :20], gold_k[:, :, :, :20], rtol=1e-6
        )
        assert int(cache.kv_len[0]) == 20

    def test_pool_alloc_release(self):
        from triton_distributed_tpu.models.paged_kv_cache import PagePool

        pool = PagePool(4)
        a = pool.allocate(3)
        assert len(set(a)) == 3
        import pytest

        with pytest.raises(RuntimeError, match="exhausted"):
            pool.allocate(2)
        pool.release(a)
        assert len(pool.allocate(4)) == 4

    def test_paged_flash_decode(self, ctx4, rng):
        """Pool-direct decode attention (page table in the BlockSpec
        index map) vs the dense golden, with shuffled page ids."""
        import jax.numpy as jnp
        from triton_distributed_tpu.ops.attention import (
            gqa_decode_reference,
            paged_flash_decode,
        )

        B, hq, hkv, hd, page, pps = 2, 4, 2, 64, 16, 4
        P = 2 * B * pps  # oversized pool; pages land scattered
        perm = rng.permutation(P)[: B * pps]
        table = jnp.asarray(perm.reshape(B, pps), jnp.int32)
        k_pool = jnp.asarray(
            rng.standard_normal((P, hkv, page, hd)), jnp.float32
        )
        v_pool = jnp.asarray(
            rng.standard_normal((P, hkv, page, hd)), jnp.float32
        )
        q = jnp.asarray(rng.standard_normal((B, hq, hd)), jnp.float32)
        lens = jnp.asarray([37, 18], jnp.int32)

        out = paged_flash_decode(q, k_pool, v_pool, table, lens)

        from triton_distributed_tpu.ops.attention.flash_decode import (
            _pages_to_dense,
        )
        k_d, v_d = _pages_to_dense(k_pool, v_pool, table)
        gold = gqa_decode_reference(q, k_d, v_d, lens)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(gold), atol=2e-5, rtol=2e-5
        )

    def test_engine_serve_paged(self, ctx4):
        """Paged serving end-to-end matches dense serving token-for-token
        (parity: reference paged megakernel serving)."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.engine import Engine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        prompt = np.arange(8, dtype=np.int32)[None].repeat(2, 0)
        prompt[1] = prompt[1][::-1]  # distinct rows
        dense = Engine(model, temperature=0.0, mode="xla").serve(
            prompt, gen_len=6
        )
        paged = Engine(
            model, temperature=0.0, mode="xla", paged=True, page_size=16
        ).serve(prompt, gen_len=6)
        np.testing.assert_array_equal(dense, paged)


def test_engine_autopads_indivisible_prompts(ctx4):
    """Prompt lengths that don't divide tp are padded internally (the
    round-1 engine raised); output matches a client-padded run."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = Engine(model, temperature=0.0, mode="xla")
    prompt = (np.arange(7, dtype=np.int32) + 1)[None].repeat(2, 0)  # s=7, tp=4
    out = eng.serve(prompt, gen_len=4)
    assert out.shape == (2, 11)
    # Same continuation as an 8-token client-side right-pad? No — the
    # engine pads AFTER rolling; equivalence golden: serve the 7-token
    # prompt via a single batch row against per-row reference.
    np.testing.assert_array_equal(out[0], out[1])


@pytest.mark.slow
def test_engine_serve_mega_multi_matches_xla():
    """Engine mode="mega" greedy at tp=1 takes the multi-step fast path
    (several steps per launch, in-kernel argmax) and must produce the
    same tokens as the xla mode."""
    import jax as _jax

    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(tp=1, devices=_jax.devices()[:1])
    try:
        model = AutoLLM.from_pretrained("tiny", ctx=ctx)
        prompt = np.arange(8, dtype=np.int32)[None].repeat(2, 0)
        gold = Engine(model, temperature=0.0, mode="xla").serve(
            prompt, gen_len=12, max_length=64
        )
        mega = Engine(model, temperature=0.0, mode="mega").serve(
            prompt, gen_len=12, max_length=64
        )
        np.testing.assert_array_equal(mega, gold)
    finally:
        mesh_mod.finalize_distributed()


@pytest.mark.slow
def test_engine_serve_mega_sampled():
    """mode="mega" with temperature>0 takes the sampled multi path
    (Gumbel-perturbed in-kernel argmax); output must be plausible
    (right shape, in-vocab) and reproducible per seed."""
    import jax as _jax

    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(tp=1, devices=_jax.devices()[:1])
    try:
        model = AutoLLM.from_pretrained("tiny", ctx=ctx)
        prompt = np.arange(8, dtype=np.int32)[None].repeat(2, 0)
        a = Engine(model, temperature=0.8, mode="mega", seed=5).serve(
            prompt, gen_len=10, max_length=64
        )
        b = Engine(model, temperature=0.8, mode="mega", seed=5).serve(
            prompt, gen_len=10, max_length=64
        )
        assert a.shape == (2, 18)
        assert (a[:, 8:] >= 0).all() and (a[:, 8:] < model.cfg.vocab_size).all()
        np.testing.assert_array_equal(a, b)  # same seed → same stream
    finally:
        mesh_mod.finalize_distributed()


@pytest.mark.slow
def test_engine_serve_mega_paged_multi_matches_dense():
    """mode="mega" + paged=True greedy takes the paged multi-step path
    (append_n single-scatter) and must match dense xla serving."""
    import jax as _jax

    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(tp=1, devices=_jax.devices()[:1])
    try:
        model = AutoLLM.from_pretrained("tiny", ctx=ctx)
        prompt = np.arange(8, dtype=np.int32)[None].repeat(2, 0)
        gold = Engine(model, temperature=0.0, mode="xla").serve(
            prompt, gen_len=12, max_length=64
        )
        paged = Engine(
            model, temperature=0.0, mode="mega", paged=True, page_size=16
        ).serve(prompt, gen_len=12, max_length=64)
        np.testing.assert_array_equal(paged, gold)
    finally:
        mesh_mod.finalize_distributed()


def test_hf_checkpoint_dir_roundtrip(ctx4, rng, tmp_path):
    """The recorded-checkpoint loader (VERDICT r2 missing #4):
    config.json + model.safetensors in true HF format, read back via
    ``AutoLLM.from_pretrained(dir)``, must produce the exact logits of
    the directly-loaded state dict."""
    import json as _json

    from safetensors.numpy import save_file

    cfg = get_config("tiny")
    state = _make_hf_state(cfg, rng)
    hf_cfg = {
        "architectures": ["Qwen3ForCausalLM"],
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": cfg.intermediate_size,
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_q_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_eps,
        "tie_word_embeddings": False,
    }
    (tmp_path / "config.json").write_text(_json.dumps(hf_cfg))
    save_file(state, str(tmp_path / "model.safetensors"))

    loaded = AutoLLM.from_pretrained(
        str(tmp_path), ctx=ctx4, dtype=jnp.float32,
        max_length=cfg.max_length,
    )
    direct = Qwen3(loaded.cfg, ctx=ctx4)
    direct.set_params(
        load_hf_state_dict(loaded.cfg, state, ctx4.axis_size("tp"))
    )
    tokens = jnp.asarray(np.arange(12) % cfg.vocab_size, jnp.int32)
    la, _ = loaded.prefill(tokens, loaded.new_cache(1), "xla")
    lb, _ = direct.prefill(tokens, direct.new_cache(1), "xla")
    np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_hf_transformers_parity(tmp_path):
    """Strongest loader+math evidence without network: a REAL
    ``transformers`` Qwen3ForCausalLM (random init) saved with
    ``save_pretrained`` and loaded by our framework must match the
    upstream implementation's logits and greedy continuation (parity:
    the reference serves actual HF checkpoints, ``models/qwen.py:147``)."""
    torch = pytest.importorskip("torch")
    tfm = pytest.importorskip("transformers")

    import jax as _jax

    from triton_distributed_tpu.runtime import mesh as mesh_mod

    hf_cfg = tfm.Qwen3Config(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,
        rope_theta=1e6,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        max_position_embeddings=64,
    )
    torch.manual_seed(0)
    hf_model = tfm.Qwen3ForCausalLM(hf_cfg).eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    prompt = np.array([3, 14, 15, 92, 65, 35, 89, 79], np.int32)
    with torch.no_grad():
        hf_logits = hf_model(
            torch.tensor(prompt[None].astype(np.int64))
        ).logits[0, -1].numpy()
        hf_gen = hf_model.generate(
            torch.tensor(prompt[None].astype(np.int64)),
            max_new_tokens=6, do_sample=False,
        )[0].numpy()

    ctx = mesh_mod.initialize_distributed(tp=2, devices=_jax.devices()[:2])
    try:
        model = AutoLLM.from_pretrained(
            str(tmp_path), ctx=ctx, dtype=jnp.float32, max_length=64,
        )
        logits, _ = model.prefill(
            jnp.asarray(prompt), model.new_cache(1), "xla"
        )
        np.testing.assert_allclose(
            np.asarray(logits), hf_logits, atol=2e-4, rtol=2e-4
        )
        out = Engine(model, temperature=0.0, mode="xla").serve(
            prompt[None], gen_len=6, max_length=64
        )
        np.testing.assert_array_equal(out[0], hf_gen)
    finally:
        mesh_mod.finalize_distributed()


@pytest.mark.parametrize("norm_topk", [True, False])
def test_hf_transformers_moe_parity(tmp_path, norm_topk):
    """MoE checkpoint path: a REAL ``transformers`` Qwen3MoeForCausalLM
    saved with ``save_pretrained`` and loaded by our framework must
    match upstream logits + greedy continuation (routes through
    ``load_hf_moe_state_dict`` via the config's expert fields) — in
    BOTH router-weight normalization modes (the HF default is False;
    official checkpoints set True — the loader must follow the config,
    not assume)."""
    torch = pytest.importorskip("torch")
    tfm = pytest.importorskip("transformers")

    import jax as _jax

    from triton_distributed_tpu.runtime import mesh as mesh_mod

    hf_cfg = tfm.Qwen3MoeConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        moe_intermediate_size=32,
        num_experts=8,
        num_experts_per_tok=2,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        head_dim=32,
        rope_theta=1e6,
        rms_norm_eps=1e-6,
        tie_word_embeddings=False,
        max_position_embeddings=64,
        norm_topk_prob=norm_topk,
        decoder_sparse_step=1,
        mlp_only_layers=[],
    )
    torch.manual_seed(0)
    hf_model = tfm.Qwen3MoeForCausalLM(hf_cfg).eval()
    hf_model.save_pretrained(tmp_path, safe_serialization=True)

    prompt = np.array([5, 44, 3, 98, 17, 62, 29, 81], np.int32)
    with torch.no_grad():
        hf_logits = hf_model(
            torch.tensor(prompt[None].astype(np.int64))
        ).logits[0, -1].numpy()
        hf_gen = hf_model.generate(
            torch.tensor(prompt[None].astype(np.int64)),
            max_new_tokens=6, do_sample=False,
        )[0].numpy()

    ctx = mesh_mod.initialize_distributed(tp=2, devices=_jax.devices()[:2])
    try:
        model = AutoLLM.from_pretrained(
            str(tmp_path), ctx=ctx, dtype=jnp.float32, max_length=64,
        )
        from triton_distributed_tpu.models.qwen_moe import Qwen3MoE

        assert isinstance(model, Qwen3MoE)
        logits, _ = model.prefill(
            jnp.asarray(prompt), model.new_cache(1), "xla"
        )
        np.testing.assert_allclose(
            np.asarray(logits), hf_logits, atol=2e-4, rtol=2e-4
        )
        out = Engine(model, temperature=0.0, mode="xla").serve(
            prompt[None], gen_len=6, max_length=64
        )
        np.testing.assert_array_equal(out[0], hf_gen)
    finally:
        mesh_mod.finalize_distributed()


def test_hf_bf16_checkpoint_loads(tmp_path):
    """A bf16-saved checkpoint (the dtype real Qwen3 releases — and the
    round-4 1.7B e2e checkpoint — ship in) must load and serve. Pinned
    against the SAME model's fp32 save: identical greedy tokens (tiny
    dims, logit gaps far above bf16 noise is not guaranteed — so
    compare prefill logits with a bf16-scale tolerance instead)."""
    torch = pytest.importorskip("torch")
    tfm = pytest.importorskip("transformers")

    import jax as _jax

    from triton_distributed_tpu.runtime import mesh as mesh_mod

    hf_cfg = tfm.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, rope_theta=1e6, rms_norm_eps=1e-6,
        tie_word_embeddings=True, max_position_embeddings=64,
    )
    torch.manual_seed(0)
    hf_model = tfm.Qwen3ForCausalLM(hf_cfg).eval()
    hf_model.save_pretrained(tmp_path / "f32", safe_serialization=True)
    hf_model.to(torch.bfloat16).save_pretrained(
        tmp_path / "bf16", safe_serialization=True
    )

    prompt = np.array([3, 14, 15, 92, 65, 35, 89, 79], np.int32)
    ctx = mesh_mod.initialize_distributed(tp=2, devices=_jax.devices()[:2])
    try:
        logits = {}
        for name in ("f32", "bf16"):
            model = AutoLLM.from_pretrained(
                str(tmp_path / name), ctx=ctx, dtype=jnp.float32,
                max_length=64,
            )
            lg, _ = model.prefill(
                jnp.asarray(prompt), model.new_cache(1), "xla"
            )
            logits[name] = np.asarray(lg)
        # bf16 weight rounding is ~2^-8 relative; tiny-dim logits are
        # O(1), so 0.05 is generous headroom without masking a wrong
        # tensor mapping (those diverge by O(1)).
        np.testing.assert_allclose(
            logits["bf16"], logits["f32"], atol=5e-2, rtol=5e-2
        )
    finally:
        mesh_mod.finalize_distributed()
