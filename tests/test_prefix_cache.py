"""Prefix cache (radix KV reuse) + chunked prefill tests.

Three layers of evidence:

- host-level radix-tree semantics (match/COW/dedupe/LRU/refcounts) and a
  randomized admit/cancel/finish stress asserting the pool invariant —
  no model, so these run in milliseconds;
- engine-level reuse proofs on the tiny model: suffix-only prefill
  (counted via ``last_stats``), bit-identical warm-vs-cold outputs,
  COW partial-tail matches, chunked-prefill interleaving, and
  eviction-pressure equivalence against dense goldens;
- the serving server's continuous-batching route.
"""

import numpy as np
import pytest

from triton_distributed_tpu.models.paged_kv_cache import PagePool
from triton_distributed_tpu.models.prefix_cache import PrefixCache


def make_pool(n):
    pool = PagePool(n + 1)
    pool.free = [p for p in pool.free if p != 0]  # page 0 = trash
    return pool, len(pool.free)


def pool_pages(pool, cache, in_flight_private=()):
    """Every page exactly once across free list / tree / in-flight."""
    owned = list(pool.free)
    owned += [n.page for n in cache.walk()]
    for pages in in_flight_private:
        owned += list(pages)
    return owned


class TestRadixTree:
    PS = 4

    def test_match_insert_dedupe_refcount(self):
        pool, cap = make_pool(16)
        pc = PrefixCache(pool, self.PS)
        toks = list(range(100, 110))  # 2.5 pages
        pages = pool.allocate(3)
        pc.insert_chain(pc.root, toks, pages)
        assert pc.node_count == 3
        assert len(pool.free) + pc.node_count == cap

        # Full-page prefix shares; the partial tail COW-matches.
        m = pc.match(toks + [1, 2, 3])
        assert [n.page for n in m.nodes] == pages[:2]
        assert m.matched_len == 10 and m.cow_len == 2
        assert all(n.refcount == 1 for n in m.nodes)
        assert m.cow_node.refcount == 1
        pc.release_match(m)
        assert all(n.refcount == 0 for n in pc.walk())

        # Matching is capped at len-1: at least one token must prefill.
        m2 = pc.match(toks[: self.PS])
        assert m2.matched_len == self.PS - 1 and m2.cow_len == self.PS - 1
        pc.release_match(m2)

        # Re-inserting an identical chain releases the duplicate pages.
        dup = pool.allocate(3)
        pc.insert_chain(pc.root, toks, dup)
        assert pc.node_count == 3  # nothing new
        assert len(pool.free) + pc.node_count == cap
        assert pc.stats["deduped_pages"] >= 2

        uniq = pool_pages(pool, pc)
        assert len(uniq) == len(set(uniq)) == cap

    def test_partial_tail_upgrade(self):
        pool, cap = make_pool(16)
        pc = PrefixCache(pool, self.PS)
        pc.insert_chain(pc.root, [1, 2, 3, 4, 5, 6], pool.allocate(2))
        # Longer chain over the same prefix upgrades the partial tail
        # node in place (its page is released, ours adopted).
        pc.insert_chain(pc.root, [1, 2, 3, 4, 5, 6, 7, 8, 9],
                        pool.allocate(3))
        m = pc.match([1, 2, 3, 4, 5, 6, 7, 8, 9, 0])
        assert m.matched_len == 9  # 2 full pages + 1-token cow
        pc.release_match(m)
        assert len(pool.free) + pc.node_count == cap

    def test_lru_eviction_order_and_pinning(self):
        pool, cap = make_pool(8)
        pc = PrefixCache(pool, self.PS)
        a = [1] * self.PS * 2
        b = [2] * self.PS * 2
        pc.insert_chain(pc.root, a, pool.allocate(2))
        pc.insert_chain(pc.root, b, pool.allocate(2))
        # Touch chain a — b becomes LRU.
        pc.release_match(pc.match(a + [9]))
        assert len(pool.free) == cap - 4
        got = pc.allocate(cap - 4 + 1)  # forces one eviction
        assert got is not None and pc.stats["evicted_pages"] >= 1
        # b's tail leaf went first.
        assert any(n.chunk[0] == 1 for n in pc.walk())
        remaining = [n for n in pc.walk() if n.chunk[0] == 2]
        assert len(remaining) < 2
        pool.release(got)

        # Pinned chains never evict: match+hold a, demand everything.
        m = pc.match(a + [9])
        before = len(pool.free)
        assert pc.allocate(before + pc.node_count) is None  # can't cover
        assert all(n.refcount == 0 or n.chunk[0] == 1 for n in pc.walk())
        pc.release_match(m)

    def test_stress_admit_cancel_finish_invariant(self):
        """Randomized interleavings must never leak, double-free, or
        alias a page: free + tree + in-flight private == capacity after
        every operation."""
        rng = np.random.default_rng(0)
        pool, cap = make_pool(24)
        pc = PrefixCache(pool, self.PS)
        bases = [list(rng.integers(1, 50, size=12)) for _ in range(3)]
        in_flight = []  # (match, private_pages, tokens, gen)

        def check():
            owned = pool_pages(
                pool, pc, [p for _, p, _, _ in in_flight]
            )
            assert len(owned) == cap, (len(owned), cap)
            assert len(set(owned)) == cap, "page aliased/double-freed"

        for step in range(400):
            op = rng.random()
            if op < 0.5 and len(in_flight) < 4:  # admit
                base = bases[rng.integers(len(bases))]
                tokens = base[: rng.integers(2, len(base) + 1)] + list(
                    rng.integers(1, 50, size=rng.integers(0, 6))
                )
                gen = int(rng.integers(1, 6))
                need = -(-(len(tokens) + gen) // self.PS)
                m = pc.match(tokens)
                priv = pc.allocate(need - len(m.nodes))
                if priv is None:
                    pc.release_match(m)
                else:
                    pc.finish_cow(m)  # cow dst = priv[0], "copied"
                    in_flight.append((m, priv, tokens, gen))
            elif in_flight:
                idx = int(rng.integers(len(in_flight)))
                m, priv, tokens, gen = in_flight.pop(idx)
                if op < 0.75:  # finish: donate pages to the tree
                    cached = len(tokens) + gen - 1
                    toks = tokens + list(
                        rng.integers(1, 50, size=gen - 1)
                    )
                    parent = m.nodes[-1] if m.nodes else pc.root
                    pc.insert_chain(
                        parent, toks[len(m.nodes) * self.PS : cached], priv
                    )
                else:  # cancel: straight back to the pool
                    pool.release(priv)
                for node in m.nodes:
                    pc.release_node(node)
            check()
        # Drain: everything lands in tree or free list, all unpinned.
        for m, priv, _, _ in in_flight:
            pool.release(priv)
            for node in m.nodes:
                pc.release_node(node)
        in_flight = []
        check()
        assert all(n.refcount == 0 for n in pc.walk())
        # Full eviction returns every page.
        pc.evict_until(cap)
        assert len(pool.free) == cap


class TestEnginePrefixReuse:
    def _goldens(self, model, reqs):
        from triton_distributed_tpu.models.engine import Engine

        return [
            Engine(model, temperature=0.0).serve(p[None], gen_len=g)[0, len(p):]
            for p, g in reqs
        ]

    def test_prefix_reuse_skips_recompute(self, ctx4):
        """Second request sharing an N-page prefix performs suffix-only
        prefill (prefill_tokens counter) with outputs bit-identical to
        the cold-cache path."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.continuous import ContinuousEngine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        shared = np.asarray(
            [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3] * 2, np.int32
        )  # 32 tokens = 2 pages at page_size=16
        pA = np.concatenate([shared, np.asarray([10, 11, 12, 13], np.int32)])
        pB = np.concatenate([shared, np.asarray([20, 21, 22, 23], np.int32)])
        goldA, goldB = self._goldens(model, [(pA, 4), (pB, 4)])

        eng = ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64,
            prefix_cache=True,
        )
        outA = eng.run([(pA, 4)])
        assert eng.last_stats["prefill_tokens"] == len(pA)  # cold: all
        assert eng.last_stats["prefix_hit_tokens"] == 0
        outB = eng.run([(pB, 4)])
        st = eng.last_stats
        assert st["prefix_hit_tokens"] == 32      # both shared pages
        assert st["prefill_tokens"] == 4          # suffix only
        np.testing.assert_array_equal(outA[0], goldA)
        np.testing.assert_array_equal(outB[0], goldB)

        # Bit-identical to the cold-cache path: a fresh engine serving B
        # from scratch produces the same tokens.
        cold = ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64,
            prefix_cache=True,
        )
        np.testing.assert_array_equal(cold.run([(pB, 4)])[0], outB[0])

        # Leak-free: every page is in the tree or the free list.
        assert len(eng.pool.free) + eng.prefix.node_count == eng._capacity

    def test_cow_partial_tail_match(self, ctx4):
        """A prefix ending inside a cached page is reused via COW: the
        page is cloned, matched positions count, outputs stay golden."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.continuous import ContinuousEngine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        rng = np.random.default_rng(3)
        head = rng.integers(1, 200, size=18).astype(np.int32)  # 1.125 pages
        pA = np.concatenate([head, np.asarray([10, 11], np.int32)])
        pB = np.concatenate([head, np.asarray([20, 21], np.int32)])
        (goldB,) = self._goldens(model, [(pB, 4)])

        eng = ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64,
            prefix_cache=True,
        )
        eng.run([(pA, 4)])
        outB = eng.run([(pB, 4)])
        st = eng.last_stats
        assert st["prefix_hit_tokens"] == 18  # 1 full page + 2-token COW
        assert st["pages_cow_copied"] == 1
        np.testing.assert_array_equal(outB[0], goldB)

    def test_chunked_prefill_interleaves_decodes(self, ctx4):
        """A long cold prompt admitted in chunks never blocks the
        running request's decode; outputs match dense goldens."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.continuous import ContinuousEngine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        rng = np.random.default_rng(7)
        long_p = rng.integers(1, 200, size=40).astype(np.int32)
        short_p = np.asarray([5, 9, 2, 4], np.int32)
        goldS, goldL = self._goldens(model, [(short_p, 8), (long_p, 3)])

        eng = ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64,
            prefix_cache=True, prefill_chunk=16,
        )
        outs = eng.run([(short_p, 8), (long_p, 3)])
        np.testing.assert_array_equal(outs[0], goldS)
        np.testing.assert_array_equal(outs[1], goldL)
        # 40-token prompt at chunk 16 → 3 chunks (+1 for the short one).
        assert eng.last_stats["prefill_chunks"] >= 4

    def test_eviction_pressure_equivalence(self, ctx4):
        """Pool sized to force LRU eviction: repeated shared-prefix
        serving never double-frees, leaks, or serves a stale page —
        outputs stay equal to the dense goldens every round."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.continuous import ContinuousEngine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        rng = np.random.default_rng(11)
        prefixes = [
            rng.integers(1, 200, size=16).astype(np.int32) for _ in range(3)
        ]
        reqs = []
        for i, pre in enumerate(prefixes):
            tail = rng.integers(1, 200, size=4 + i).astype(np.int32)
            reqs.append((np.concatenate([pre, tail]), 3))
        golds = self._goldens(model, reqs)

        # 2 slots × 3 pages/req worst case, but only 7 pages: admission
        # must evict cached chains to serve new prefixes.
        eng = ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64,
            prefix_cache=True, num_pages=7,
        )
        for round_ in range(3):
            outs = eng.run(reqs)
            for got, gold in zip(outs, golds):
                np.testing.assert_array_equal(got, gold)
            assert (
                len(eng.pool.free) + eng.prefix.node_count == eng._capacity
            )
            owned = pool_pages(eng.pool, eng.prefix)
            assert len(owned) == len(set(owned))
        assert eng.prefix.stats["evicted_pages"] > 0

    def test_engine_paged_prefix_across_serves(self, ctx4):
        """Engine(paged, prefix_cache): the tree persists across serve()
        calls — the second call prefills only the uncached suffix and
        returns the same tokens as a cold engine."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.engine import Engine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        shared = np.asarray(
            [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7, 9, 3], np.int32
        )
        pA = np.concatenate([shared, np.asarray([10, 11, 12, 13], np.int32)])
        pB = np.concatenate([shared, np.asarray([20, 21, 22, 23], np.int32)])
        gold = Engine(model, temperature=0.0).serve(pB[None], gen_len=4)

        eng = Engine(
            model, temperature=0.0, paged=True, page_size=16,
            prefix_cache=True,
        )
        eng.serve(pA[None], gen_len=4, max_length=64)
        assert eng.last_stats["prefix_hit_tokens"] == 0
        out = eng.serve(pB[None], gen_len=4, max_length=64)
        np.testing.assert_array_equal(out, gold)
        assert eng.last_stats["prefix_hit_tokens"] == 16
        assert eng.last_stats["prefill_tokens"] == 4

    def test_engine_paged_prefix_boundary_capacity(self, ctx4):
        """true_len + gen_len - 1 == max_length (the last sampled token
        is never appended) must serve: page reservation counts written
        positions, not prompt+gen."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.engine import Engine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        prompt = np.arange(1, 62, dtype=np.int32)[None]  # 61 tokens
        gold = Engine(model, temperature=0.0).serve(
            prompt, gen_len=4, max_length=64
        )
        eng = Engine(
            model, temperature=0.0, paged=True, page_size=16,
            prefix_cache=True, prefill_chunk=61,  # unrounded width too
        )
        out = eng.serve(prompt, gen_len=4, max_length=64)  # 61+4-1 = 64
        np.testing.assert_array_equal(out, gold)

    def test_engine_cow_pin_cannot_starve_pool(self, ctx4):
        """A COW pin covers none of the row's page budget; when it alone
        starves allocation the engine degrades (drop COW, then cold)
        instead of crashing — outputs stay golden."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.engine import Engine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        p1 = np.arange(1, 25, dtype=np.int32)[None]  # 24 tokens, pps=2
        eng = Engine(
            model, temperature=0.0, paged=True, page_size=16,
            prefix_cache=True,
        )
        eng.serve(p1, gen_len=4, max_length=32)
        # Shares 8 tokens with the cached full page → COW pin; the
        # 2-page pool can't hold the pin + 2 fresh pages.
        p2 = np.concatenate(
            [p1[0][:8], 90 + np.arange(16, dtype=np.int32)]
        )[None]
        gold = Engine(model, temperature=0.0).serve(
            p2, gen_len=4, max_length=32
        )
        np.testing.assert_array_equal(
            eng.serve(p2, gen_len=4, max_length=32), gold
        )

    def test_engine_prefix_requires_paged(self, ctx4):
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.engine import Engine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        with pytest.raises(ValueError, match="requires paged"):
            Engine(model, prefix_cache=True)

    def test_randomized_engine_page_accounting(self, ctx4):
        """Random admit/finish interleavings across runs (mixed lengths,
        eos early-exit) keep the pool invariant: free + tree == capacity
        with no aliased pages."""
        from triton_distributed_tpu.models import AutoLLM
        from triton_distributed_tpu.models.continuous import ContinuousEngine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        rng = np.random.default_rng(5)
        eng = ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64,
            prefix_cache=True, num_pages=9,
        )
        base = rng.integers(1, 200, size=20).astype(np.int32)
        for round_ in range(3):
            reqs = []
            for _ in range(int(rng.integers(1, 4))):
                cut = int(rng.integers(1, len(base)))
                tail = rng.integers(1, 200, size=int(rng.integers(0, 5)))
                prompt = np.concatenate([base[:cut], tail]).astype(np.int32)
                reqs.append((prompt, int(rng.integers(1, 5))))
            eng.run(reqs)
            assert (
                len(eng.pool.free) + eng.prefix.node_count == eng._capacity
            )
            owned = pool_pages(eng.pool, eng.prefix)
            assert len(owned) == len(set(owned))
            assert all(n.refcount == 0 for n in eng.prefix.walk())


def test_server_continuous_round_trip(ctx4):
    """The model server routes 'requests' payloads to the continuous
    engine and reports prefix-cache stats."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.serving import ModelServer, request

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, prefix_cache=True
    )
    prompts = [[5, 9, 2, 4], [5, 9, 2, 4, 7, 1, 3, 8]]
    gold = eng.run([(np.asarray(p, np.int32), 3) for p in prompts])

    server = ModelServer(eng).start()
    try:
        resp = request(
            server.host, server.port,
            {"requests": prompts, "gen_lens": [3, 3]},
        )
        for got, g in zip(resp["outputs"], gold):
            np.testing.assert_array_equal(np.asarray(got, np.int32), g)
        assert "prefix_hit_rate" in resp["stats"]
        stats = request(server.host, server.port, {"cmd": "stats"})["stats"]
        assert "prefill_tokens" in stats
    finally:
        server.shutdown()
