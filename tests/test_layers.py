"""Layer tests (parity: reference test_tp_mlp.py / test_tp_attn.py —
golden = replicated jnp forward, compare with allclose)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.layers.tp_mlp import TPMLP


def _golden_mlp(x, gate, up, down):
    h = jax.nn.silu(x @ gate) * (x @ up)
    return h @ down


@pytest.mark.parametrize("mode", ["xla", "pallas", "xla_ar", "pallas_ar"])
def test_tp_mlp(ctx4, rng, mode):
    d_model, d_ff, m = 64, 256, 32
    gate = jnp.asarray(rng.standard_normal((d_model, d_ff)) * 0.05, jnp.float32)
    up = jnp.asarray(rng.standard_normal((d_model, d_ff)) * 0.05, jnp.float32)
    down = jnp.asarray(rng.standard_normal((d_ff, d_model)) * 0.05, jnp.float32)
    x = jnp.asarray(rng.standard_normal((m, d_model)) * 0.1, jnp.float32)

    layer = TPMLP(d_model, d_ff, dtype=jnp.float32, ctx=ctx4)
    layer.load(gate, up, down)
    out = layer.forward(x, mode=mode)

    ref = _golden_mlp(x, gate, up, down)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4, rtol=2e-4)


def _golden_attn(x, wq, wk, wv, wo, hq, hkv, hd, theta=1e6, qn=None, kn=None):
    from triton_distributed_tpu.ops.attention.flash_attention import mha_reference
    from triton_distributed_tpu.ops.attention.rope import apply_rope
    from triton_distributed_tpu.layers.tp_attn import _rms_head

    s = x.shape[0]
    q = (x @ wq).reshape(s, hq, hd)
    k = (x @ wk).reshape(s, hkv, hd)
    v = (x @ wv).reshape(s, hkv, hd)
    q = _rms_head(q, qn)
    k = _rms_head(k, kn)
    pos = jnp.arange(s)
    q = apply_rope(q.swapaxes(0, 1), pos, theta)
    k = apply_rope(k.swapaxes(0, 1), pos, theta)
    o = mha_reference(q[None], k[None], v.swapaxes(0, 1)[None], causal=True)[0]
    return o.swapaxes(0, 1).reshape(s, hq * hd) @ wo


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["xla", "pallas"])
def test_tp_attn_prefill(ctx4, rng, mode):
    from triton_distributed_tpu.layers.tp_attn import TPAttn

    d, hq, hkv, hd, s = 64, 8, 4, 32, 256
    f32 = jnp.float32
    wq = jnp.asarray(rng.standard_normal((d, hq * hd)) * 0.05, f32)
    wk = jnp.asarray(rng.standard_normal((d, hkv * hd)) * 0.05, f32)
    wv = jnp.asarray(rng.standard_normal((d, hkv * hd)) * 0.05, f32)
    wo = jnp.asarray(rng.standard_normal((hq * hd, d)) * 0.05, f32)
    qn = jnp.asarray(1.0 + 0.1 * rng.standard_normal(hd), f32)
    kn = jnp.asarray(1.0 + 0.1 * rng.standard_normal(hd), f32)
    x = jnp.asarray(rng.standard_normal((s, d)) * 0.1, f32)

    layer = TPAttn(d, hq, hkv, hd, dtype=f32, ctx=ctx4)
    layer.load(wq, wk, wv, wo, qn, kn)
    out = layer.prefill(x, mode=mode)
    ref = _golden_attn(x, wq, wk, wv, wo, hq, hkv, hd, qn=qn, kn=kn)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-4,
                               rtol=5e-4)
