"""Telemetry subsystem tests (ISSUE 5, docs/observability.md).

Covers the four obs/ pillars and their serving integration:
histogram bucket math against numpy percentiles, registry
thread-safety, event-ring overflow/seq continuity, Prometheus
exposition grammar, per-request timelines (TTFT/TPOT/queue-wait/e2e
with PR 3 status labels) from a real multi-request
``ContinuousEngine.run()``, the unified core ``last_stats`` schema,
``trace_span``'s numeric-native event-ring mirror, and the server's
``metrics``/``events`` verbs — including a scrape answered
MID-generation.
"""

import re
import threading
import time

import numpy as np
import pytest

from triton_distributed_tpu import obs
from triton_distributed_tpu.obs import events as obs_events
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs.metrics import (
    Registry,
    log_buckets,
    prometheus_text,
)
from triton_distributed_tpu.obs.timeline import Timeline, observe_request


@pytest.fixture(autouse=True)
def _fresh_telemetry(fresh_telemetry):
    """Every test here asserts absolute totals — make the shared
    reset fixture (tests/conftest.py) autouse file-wide."""
    yield


# -- metrics registry ------------------------------------------------------


def test_counter_gauge_basics():
    reg = Registry(enabled=True)
    c = reg.counter("t_total", "help", labels=("verb",))
    c.inc(verb="a")
    c.inc(2, verb="a")
    c.inc(verb="b")
    assert c.value(verb="a") == 3 and c.value(verb="b") == 1
    with pytest.raises(ValueError):
        c.inc(-1, verb="a")  # counters only go up
    with pytest.raises(ValueError):
        c.inc(wrong="label")
    g = reg.gauge("t_gauge")
    g.set(5)
    g.add(-2)
    assert g.value() == 3
    # Same name + kind + labels: the SAME family (engines re-register).
    assert reg.counter("t_total", labels=("verb",)) is c
    with pytest.raises(ValueError):
        reg.gauge("t_total")  # kind mismatch
    with pytest.raises(ValueError):
        reg.counter("t_total", labels=("other",))  # label mismatch
    h = reg.histogram("t_seconds", buckets=(1.0, 10.0))
    assert reg.histogram("t_seconds", buckets=(1.0, 10.0)) is h
    with pytest.raises(ValueError):
        reg.histogram("t_seconds", buckets=(1.0, 100.0))  # bucket mismatch


def test_histogram_percentiles_vs_numpy():
    """Bucket-derived p50/p90/p99 stay within one log-bucket's width of
    exact numpy percentiles — the accuracy contract fixed edges buy."""
    per_decade = 4
    factor = 10 ** (1 / per_decade)
    reg = Registry(enabled=True)
    h = reg.histogram(
        "t_lat", buckets=log_buckets(1e-4, 100.0, per_decade)
    )
    rng = np.random.default_rng(7)
    samples = rng.lognormal(mean=-3.0, sigma=1.2, size=20_000)
    for s in samples:
        h.observe(float(s))
    assert h.count() == len(samples)
    for q in (0.50, 0.90, 0.99):
        est = h.quantile(q)
        true = float(np.percentile(samples, q * 100))
        assert true / factor <= est <= true * factor, (
            f"p{int(q * 100)}: est {est} vs true {true}"
        )
    # Empty series has no quantiles.
    assert reg.histogram("t_empty").quantile(0.5) is None


def test_histogram_overflow_bucket_clamps():
    reg = Registry(enabled=True)
    h = reg.histogram("t_of", buckets=(1.0, 10.0))
    h.observe(1e9)
    assert h.quantile(0.5) == 10.0  # clamped to the last finite edge
    snap = reg.snapshot()["t_of"]["series"][0]
    assert snap["count"] == 1 and snap["buckets"]["counts"][-1] == 1


def test_registry_thread_safety():
    """Concurrent increments/observations from many threads lose
    nothing: totals are exact, not approximate."""
    reg = Registry(enabled=True)
    c = reg.counter("t_total")
    h = reg.histogram("t_h", buckets=(1.0, 2.0, 4.0))
    N, T = 5_000, 8

    def work():
        for i in range(N):
            c.inc()
            h.observe(float(i % 5))

    threads = [threading.Thread(target=work) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == N * T
    assert h.count() == N * T


def test_disabled_mode_is_noop():
    obs.set_enabled(False)
    obs_metrics.counter("t_off_total").inc(5)
    obs_metrics.histogram("t_off_h").observe(1.0)
    seq = obs_events.emit("e", x=1)
    assert seq == 0
    obs.set_enabled(True)
    assert obs_metrics.counter("t_off_total").value() == 0
    assert obs_metrics.histogram("t_off_h").count() == 0
    assert obs_events.default_ring().tail(0)[0] == []


# -- exposition grammar ----------------------------------------------------

_COMMENT_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?$")
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*")*\})?'
    r" (-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|[+-]Inf|NaN)$"
)


def assert_prometheus_parses(text: str) -> dict:
    """Every line matches the exposition grammar; returns
    ``{metric_name: [sample lines]}`` for follow-on assertions."""
    samples: dict = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert _COMMENT_RE.match(line), f"bad comment line: {line!r}"
            continue
        assert _SAMPLE_RE.match(line), f"bad sample line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        samples.setdefault(name, []).append(line)
    return samples


def test_prometheus_text_grammar_and_consistency():
    reg = Registry(enabled=True)
    reg.counter("t_req_total", "requests", labels=("verb",)).inc(
        3, verb="ping"
    )
    # Label values needing escapes must not break the grammar.
    reg.counter("t_req_total", labels=("verb",)).inc(
        verb='we"ird\\label\nvalue'
    )
    reg.gauge("t_pages", "free pages").set(17.5)
    h = reg.histogram("t_lat_seconds", "latency", labels=("status",),
                      buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v, status="ok")
    text = prometheus_text(reg)
    samples = assert_prometheus_parses(text)
    assert "t_req_total" in samples and "t_pages" in samples
    # Histogram exposition: cumulative buckets, +Inf == _count.
    buckets = samples["t_lat_seconds_bucket"]
    counts = [int(line.rsplit(" ", 1)[1]) for line in buckets]
    assert counts == sorted(counts), "bucket counts must be cumulative"
    assert 'le="+Inf"' in buckets[-1]
    count_line = samples["t_lat_seconds_count"][0]
    assert int(count_line.rsplit(" ", 1)[1]) == counts[-1] == 5
    sum_line = samples["t_lat_seconds_sum"][0]
    assert float(sum_line.rsplit(" ", 1)[1]) == pytest.approx(56.05)


# -- event ring ------------------------------------------------------------


def test_ring_overflow_and_seq_continuity():
    ring = obs_events.EventRing(capacity=16, enabled=True)
    for i in range(100):
        ring.emit("tick", i=i)
    evts, dropped = ring.tail(0)
    assert len(evts) == 16 and dropped == 84
    seqs = [e.seq for e in evts]
    assert seqs == list(range(85, 101)), "survivors are the NEWEST 16"
    assert [e.fields["i"] for e in evts] == list(range(84, 100))
    # Drop-free incremental tailing: a consumer keeping up sees gaps
    # of exactly zero.
    last = seqs[-1]
    ring.emit("tick", i=100)
    evts2, dropped2 = ring.tail(last)
    assert dropped2 == 0 and [e.seq for e in evts2] == [last + 1]
    # A consumer that stalled past capacity sees the drop count.
    for i in range(40):
        ring.emit("tick", i=200 + i)
    evts3, dropped3 = ring.tail(last + 1)
    assert dropped3 == 40 - 16 + 0 and len(evts3) == 16
    # limit is a page size: it keeps the OLDEST available, dropped
    # counts only ring-overwritten events, and paging on the returned
    # seqs walks the whole backlog without skipping anything.
    evts4, dropped4 = ring.tail(0, limit=4)
    assert len(evts4) == 4
    assert dropped4 == evts4[0].seq - 1  # only the overwritten prefix
    paged = list(evts4)
    while True:
        page, d = ring.tail(paged[-1].seq, limit=4)
        assert d == 0  # nothing overwritten mid-pagination
        if not page:
            break
        paged.extend(page)
    full, _ = ring.tail(evts4[0].seq - 1)
    assert [e.seq for e in paged] == [e.seq for e in full]
    # A negative cursor clamps to 0 — never phantom `dropped` counts
    # beyond what the ring actually overwrote.
    neg_evts, neg_dropped = ring.tail(-100)
    zero_evts, zero_dropped = ring.tail(0)
    assert [e.seq for e in neg_evts] == [e.seq for e in zero_evts]
    assert neg_dropped == zero_dropped


def test_ring_timestamps_monotonic():
    ring = obs_events.EventRing(capacity=8, enabled=True)
    ring.emit("a")
    time.sleep(0.002)
    ring.emit("b")
    evts, _ = ring.tail(0)
    assert evts[0].t <= evts[1].t


def test_ring_tail_kind_filter():
    """ISSUE 8 satellite: ``tail(kind=...)`` pulls one event stream
    server-side. The filter applies after the drop count (overwritten
    events' kinds are unknowable) and before ``limit`` (a page is
    ``limit`` MATCHING events)."""
    ring = obs_events.EventRing(capacity=32, enabled=True)
    for i in range(10):
        ring.emit("span", i=i)
        ring.emit("fault", i=i)
    spans, dropped = ring.tail(0, kind="span")
    assert dropped == 0 and len(spans) == 10
    assert all(e.kind == "span" for e in spans)
    assert [e.fields["i"] for e in spans] == list(range(10))
    # limit counts MATCHING events, not scanned events.
    page, _ = ring.tail(0, limit=3, kind="fault")
    assert [e.fields["i"] for e in page] == [0, 1, 2]
    assert all(e.kind == "fault" for e in page)
    # Paging by the returned seq walks the filtered stream completely.
    got = list(page)
    while True:
        page, d = ring.tail(got[-1].seq, limit=3, kind="fault")
        assert d == 0
        if not page:
            break
        got.extend(page)
    assert [e.fields["i"] for e in got] == list(range(10))
    # No matches at all: empty page, drop count still exact.
    none, d = ring.tail(0, kind="nope")
    assert none == [] and d == 0
    # Drop accounting is unchanged by the filter: overflow the ring.
    ring2 = obs_events.EventRing(capacity=8, enabled=True)
    for i in range(20):
        ring2.emit("a" if i % 2 else "b", i=i)
    filt, dropped2 = ring2.tail(0, kind="a")
    allv, dropped_all = ring2.tail(0)
    assert dropped2 == dropped_all == 12
    assert [e.fields["i"] for e in filt] == [
        e.fields["i"] for e in allv if e.kind == "a"
    ]


# -- trace_span → event ring -------------------------------------------------


def test_trace_span_numeric_args_survive_in_ring():
    """Regression (ISSUE 5 satellite): float span args — e.g. spec
    accept rates — must land in the event ring as NUMBERS, whatever
    the profiler's metadata does with them."""
    from triton_distributed_tpu.runtime.profiling import trace_span

    with trace_span("t:span", slot=3, rate=0.375, tag=[1, 2]):
        pass
    evts, _ = obs_events.default_ring().tail(0)
    spans = [e for e in evts if e.kind == "span"
             and e.fields.get("name") == "t:span"]
    assert len(spans) == 1
    f = spans[0].fields
    assert f["slot"] == 3 and isinstance(f["slot"], int)
    assert f["rate"] == 0.375 and isinstance(f["rate"], float)
    assert f["tag"] == "[1, 2]"  # non-numerics stringify
    assert isinstance(f["dur_s"], float) and f["dur_s"] >= 0.0
    # _ring=False: sites with a dedicated richer event (spec_verify)
    # opt out of the duplicate span entry.
    with trace_span("t:quiet", slot=1, _ring=False):
        pass
    evts, _ = obs_events.default_ring().tail(0)
    assert not any(e.fields.get("name") == "t:quiet" for e in evts
                   if e.kind == "span")
    # Arg keys colliding with the event's own fields survive under a
    # ctx_ prefix instead of silently dropping the span event.
    with trace_span("t:clash", dur_s=9.0, kind="x"):
        pass
    evts, _ = obs_events.default_ring().tail(0)
    clash = [e for e in evts if e.kind == "span"
             and e.fields.get("name") == "t:clash"]
    assert len(clash) == 1
    assert clash[0].fields["ctx_dur_s"] == 9.0
    assert clash[0].fields["ctx_kind"] == "x"
    assert clash[0].fields["dur_s"] >= 0.0


def test_trace_span_float_probe_cached(monkeypatch):
    """Regression: a profiler that rejects float metadata pays ONE
    failed TraceAnnotation construction ever — the rejection is
    remembered (``_FLOAT_META_OK``) and later float spans go straight
    to the stringified form instead of raising/catching per span."""
    from triton_distributed_tpu.runtime import profiling

    attempts = []

    class RejectsFloats:
        def __init__(self, name, **kwargs):
            attempts.append(kwargs)
            if any(isinstance(v, float) for v in kwargs.values()):
                raise TypeError("no float metadata")

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setattr(
        profiling.jax.profiler, "TraceAnnotation", RejectsFloats
    )
    monkeypatch.setattr(profiling, "_FLOAT_META_OK", None)
    monkeypatch.setattr(profiling, "_STR_META_ONLY", False)
    with profiling.trace_span("t:probe1", rate=0.5):
        pass
    # First float span: failed float probe + stringified retry.
    assert len(attempts) == 2
    assert profiling._FLOAT_META_OK is False
    with profiling.trace_span("t:probe2", rate=0.25):
        pass
    # Cached: exactly one (stringified) construction, no re-probe.
    assert len(attempts) == 3
    assert isinstance(attempts[-1]["rate"], str)
    # The ring mirror still keeps the float native either way.
    evts, _ = obs_events.default_ring().tail(0)
    p2 = [e for e in evts if e.kind == "span"
          and e.fields.get("name") == "t:probe2"]
    assert len(p2) == 1 and p2[0].fields["rate"] == 0.25

    # A WHOLLY broken profiler (every construction raises) settles the
    # FLOAT probe (later float spans skip the native-float rung) but
    # NOT the stringify ladder position — a total failure may be
    # transient and must not downgrade future spans' metadata.
    class AlwaysRaises:
        def __init__(self, name, **kwargs):
            attempts.append(kwargs)
            raise RuntimeError("profiler API mismatch")

    monkeypatch.setattr(
        profiling.jax.profiler, "TraceAnnotation", AlwaysRaises
    )
    monkeypatch.setattr(profiling, "_FLOAT_META_OK", None)
    monkeypatch.setattr(profiling, "_STR_META_ONLY", False)
    n0 = len(attempts)
    with profiling.trace_span("t:broken1", rate=0.5):
        pass
    # Unsettled ladder: float probe + int retry + uniform stringify.
    assert len(attempts) == n0 + 3
    assert profiling._FLOAT_META_OK is False
    assert profiling._STR_META_ONLY is False
    with profiling.trace_span("t:broken2", rate=0.5):
        pass
    # Float probe settled: the float rung is skipped, the rest of the
    # ladder still runs (the failure could have been transient).
    assert len(attempts) == n0 + 5


def test_trace_span_uniform_stringify_fallback(monkeypatch):
    """Regression (ISSUE 8): a profiler that rejects a NON-float arg
    type too (here: any non-str metadata) used to lose the span — and
    its args — on the retry path. The uniform stringify rung must keep
    the span alive with all-string args, remember the ladder position,
    and leave the ring mirror's numerics native."""
    from triton_distributed_tpu.runtime import profiling

    entered = []

    class StrOnly:
        def __init__(self, name, **kwargs):
            if any(not isinstance(v, str) for v in kwargs.values()):
                raise TypeError("string metadata only")
            self.kwargs = kwargs

        def __enter__(self):
            entered.append(self.kwargs)
            return self

        def __exit__(self, *exc):
            return False

    monkeypatch.setattr(
        profiling.jax.profiler, "TraceAnnotation", StrOnly
    )
    monkeypatch.setattr(profiling, "_FLOAT_META_OK", None)
    monkeypatch.setattr(profiling, "_STR_META_ONLY", False)
    # Mixed arg types INCLUDING a non-float the old retry path lost:
    # floats stringified on rung 2 still left the int native, so rung
    # 2 failed too and the span vanished.
    with profiling.trace_span("t:mixed", rate=0.5, slot=3, tag="x"):
        pass
    assert len(entered) == 1  # the span survived
    assert entered[0] == {"rate": "0.5", "slot": "3", "tag": "x"}
    assert profiling._STR_META_ONLY is True
    # Settled: the next span goes straight to the stringify rung.
    with profiling.trace_span("t:mixed2", slot=4):
        pass
    assert len(entered) == 2
    assert entered[1] == {"slot": "4"}
    # Ring mirror keeps numerics native regardless of profiler mode.
    evts, _ = obs_events.default_ring().tail(0)
    mine = [e for e in evts if e.kind == "span"
            and e.fields.get("name") == "t:mixed"]
    assert len(mine) == 1
    assert mine[0].fields["rate"] == 0.5 and mine[0].fields["slot"] == 3


# -- timelines ---------------------------------------------------------------


def test_timeline_math_and_latch_once():
    tl = Timeline()
    tl.enqueue_t = 100.0
    tl.admit_t = 100.5
    tl.first_chunk_t = 100.75
    tl.first_token_t = 101.0
    tl.finish_t = 103.0
    tl.tokens_out = 5
    assert tl.queue_wait_s == 0.5
    assert tl.prefill_dispatch_s == 0.25
    assert tl.ttft_s == 1.0
    assert tl.e2e_s == 3.0
    assert tl.tpot_s == pytest.approx(2.0 / 4)
    # The latch is on status: first finish() wins, and the manually
    # set finish_t stamp is kept (stamps latch on first write).
    assert tl.finish("ok") is True
    assert tl.finish_t == 103.0
    tl2 = Timeline()
    tl2.stamp_enqueue()
    assert tl2.finish("failed") is True
    assert tl2.finish("ok") is False and tl2.status == "failed"
    # A 1-token request has no decode phase → no TPOT sample.
    tl3 = Timeline()
    tl3.enqueue_t, tl3.first_token_t, tl3.finish_t = 0.0, 1.0, 2.0
    tl3.tokens_out = 1
    assert tl3.tpot_s is None


def test_observe_request_skips_missing_stamps():
    reg = Registry(enabled=True)
    tl = Timeline()
    tl.stamp_enqueue()
    tl.finish("overloaded")  # shed: never admitted, no first token
    observe_request(tl, reg)
    snap = reg.snapshot()
    assert snap["tdt_requests_total"]["series"][0]["labels"] == {
        "status": "overloaded"
    }
    assert "tdt_request_ttft_seconds" not in snap


# -- engine integration ------------------------------------------------------


def _tiny_continuous(ctx, **kw):
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx)
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_length", 64)
    return model, ContinuousEngine(model, **kw)


def test_continuous_run_populates_latency_histograms(ctx4):
    """Acceptance (ISSUE 5): TTFT/TPOT/queue-wait/e2e histograms with
    p50/p90/p99 appear for a multi-request run, labeled with PR 3
    finish statuses."""
    from triton_distributed_tpu.models.continuous import Request

    _model, eng = _tiny_continuous(ctx4)
    reqs = [
        Request(np.asarray([5, 9, 2, 4], np.int32), 8),
        Request(np.asarray([7, 1, 3, 8, 6, 2], np.int32), 6),
        Request(np.asarray([5, 9, 2], np.int32), 4),
        # Expired before admission → deadline_exceeded label.
        Request(np.asarray([4, 4, 4], np.int32), 4, deadline_s=-1.0),
    ]
    results = eng.run(reqs, results=True)
    statuses = [r.status for r in results]
    assert statuses[:3] == ["ok"] * 3
    assert statuses[3] == "deadline_exceeded"

    snap = obs_metrics.default_registry().snapshot()
    for name in ("tdt_request_ttft_seconds", "tdt_request_tpot_seconds",
                 "tdt_request_e2e_seconds"):
        series = snap[name]["series"]
        ok = [s for s in series if s["labels"] == {"status": "ok"}]
        assert ok and ok[0]["count"] == 3, f"{name}: {series}"
        for q in ("p50", "p90", "p99"):
            assert ok[0][q] is not None and ok[0][q] > 0
    qw = snap["tdt_request_queue_wait_seconds"]["series"]
    assert qw and qw[0]["count"] >= 3  # unlabeled: all admitted requests
    pd = snap["tdt_request_prefill_dispatch_seconds"]["series"]
    assert pd and pd[0]["count"] == 3  # admit → first chunk, admitted only
    sizes = snap["tdt_request_tokens_out"]["series"]
    assert sizes and sizes[0]["count"] == 3 and sizes[0]["sum"] == 8 + 6 + 4
    got = {s["labels"]["status"]: s["value"]
           for s in snap["tdt_requests_total"]["series"]}
    assert got == {"ok": 3, "deadline_exceeded": 1}
    assert snap["tdt_tokens_out_total"]["series"][0]["value"] == 8 + 6 + 4
    # Counters mirror last_stats live.
    assert (snap["tdt_engine_decode_steps_total"]["series"][0]["value"]
            == eng.last_stats["decode_steps"])
    # Lifecycle events landed in the ring.
    kinds = {e.kind for e in obs_events.default_ring().tail(0)[0]}
    assert {"admit", "evict", "deadline"} <= kinds


def test_core_stats_keys_unified(ctx4):
    """Satellite (ISSUE 5): Engine.last_stats and
    ContinuousEngine.last_stats expose ONE shared core key set
    (models/stats.py) — the shapes must not drift again."""
    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.engine import Engine
    from triton_distributed_tpu.models.stats import (
        CORE_STATS_KEYS,
        missing_core_stats,
    )

    model, ceng = _tiny_continuous(ctx4)
    ceng.run([([5, 9, 2, 4], 4)])
    assert missing_core_stats(ceng.last_stats) == []

    feng = Engine(model, temperature=0.0)
    feng.serve(np.asarray([[5, 9, 2, 4]], np.int32), gen_len=4)
    assert missing_core_stats(feng.last_stats) == []

    # The schema itself stays honest: every core key is a string and
    # the set is non-trivial.
    assert len(CORE_STATS_KEYS) >= 5


def test_outputs_bit_identical_with_telemetry_off(ctx4):
    """Acceptance (ISSUE 5): telemetry never touches the token path —
    the same workload decodes to identical tokens enabled or
    disabled."""
    prompts = [([5, 9, 2, 4], 8), ([7, 1, 3, 8, 6, 2], 6)]
    _m1, e1 = _tiny_continuous(ctx4, prefix_cache=True, prefill_chunk=16)
    on = [o.tolist() for o in e1.run(prompts)]
    obs.set_enabled(False)
    _m2, e2 = _tiny_continuous(ctx4, prefix_cache=True, prefill_chunk=16)
    off = [o.tolist() for o in e2.run(prompts)]
    obs.set_enabled(True)
    assert on == off


# -- server integration ------------------------------------------------------


def test_server_metrics_verb_and_grammar(ctx4):
    """Acceptance (ISSUE 5): {"cmd": "metrics"} returns Prometheus text
    that parses line-by-line, plus the JSON snapshot; {"cmd": "events"}
    tails the ring through the wire."""
    from triton_distributed_tpu.serving.server import ModelServer, request

    _model, eng = _tiny_continuous(ctx4)
    server = ModelServer(eng).start()
    try:
        r = request(server.host, server.port,
                    {"requests": [[5, 9, 2, 4]], "gen_lens": [4]})
        assert r["results"][0]["status"] == "ok"
        m = request(server.host, server.port, {"cmd": "metrics"})
        samples = assert_prometheus_parses(m["prometheus"])
        assert "tdt_requests_total" in samples
        assert "tdt_request_ttft_seconds_bucket" in samples
        snap = m["metrics"]
        assert snap["tdt_server_requests_total"]["type"] == "counter"
        ttft = snap["tdt_request_ttft_seconds"]["series"][0]
        assert ttft["count"] >= 1 and ttft["p50"] is not None
        ev = request(server.host, server.port,
                     {"cmd": "events", "since": 0})
        kinds = [e["kind"] for e in ev["events"]]
        assert "admit" in kinds and ev["next_since"] >= 1
        # Incremental tail from next_since is drop-free and empty-ish.
        ev2 = request(server.host, server.port,
                      {"cmd": "events", "since": ev["next_since"]})
        assert ev2["dropped"] == 0
        # since/limit validation: wrong types and negative cursors are
        # the CLIENT's fault (bad_request, never `internal`) — and a
        # negative since must not manufacture phantom `dropped` counts.
        for bad in ({"since": []}, {"since": "abc"}, {"since": -5},
                    {"limit": -1}):
            with pytest.raises(RuntimeError, match="bad_request"):
                request(server.host, server.port,
                        {"cmd": "events", **bad})
        # JSON null still reads as "from the start" / "no cap".
        ev3 = request(server.host, server.port,
                      {"cmd": "events", "since": None, "limit": None})
        assert [e["kind"] for e in ev3["events"]] == kinds
        s = request(server.host, server.port, {"cmd": "stats"})
        assert s["stats"]["server"]["uptime_s"] >= 0.0
        assert "snapshot_at" in s["stats"]["server"]
    finally:
        request(server.host, server.port, {"cmd": "shutdown"})
        server.shutdown()


def test_server_metrics_answers_mid_generation(ctx4):
    """Acceptance (ISSUE 5): the metrics verb never takes the engine
    lock — a scrape completes while a generation batch is in flight."""
    from triton_distributed_tpu.serving.server import ModelServer, request

    _model, eng = _tiny_continuous(ctx4)
    server = ModelServer(eng).start()
    errors: list = []

    def generate():
        try:
            request(server.host, server.port,
                    {"requests": [[5, 9, 2, 4, 7, 1, 3, 8]],
                     "gen_lens": [40]}, timeout=300)
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    t = threading.Thread(target=generate)
    t.start()
    try:
        # Scrape repeatedly while the batch decodes; at least one
        # scrape must START while the generation is in flight and
        # complete — asserted directly, so a metrics verb that
        # regressed into taking the engine lock fails this test
        # instead of silently passing after the batch drains.
        answered_mid_flight = False
        while t.is_alive():
            m = request(server.host, server.port, {"cmd": "metrics"},
                        timeout=30)
            assert "prometheus" in m and "metrics" in m
            assert_prometheus_parses(m["prometheus"])
            if t.is_alive():
                # The response arrived while the batch was STILL
                # generating — a lock-blocked scrape would only have
                # returned after the generation drained.
                answered_mid_flight = True
                break
        assert answered_mid_flight, (
            "generation finished before any scrape started — raise "
            "gen_lens so the batch outlives the first metrics request"
        )
    finally:
        t.join(timeout=300)
        request(server.host, server.port, {"cmd": "shutdown"})
        server.shutdown()
    assert not errors
