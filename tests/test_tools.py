"""Autotuner + perf-model coverage.

Parity: the reference exercises its autotuner through the kernel tests
(``contextual_autotune`` wrapping ag_gemm runs) and uses the perf models
for pruning; here both get direct unit tests.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.tools import (
    ChipSpec,
    Config,
    autotune,
    chip_spec,
    estimate_all_gather_time_ms,
    estimate_all_reduce_time_ms,
    estimate_gemm_time_ms,
    estimate_reduce_scatter_time_ms,
    prune_configs_by_model,
)
from triton_distributed_tpu.tools.autotuner import Autotuner, KernelError


def test_autotune_picks_best_and_caches(tmp_path, monkeypatch):
    monkeypatch.setenv("TDT_AUTOTUNE_LOG_DIR", str(tmp_path))
    calls = []

    def op(x, tile=128):
        calls.append(tile)
        if tile == 512:
            raise ValueError("config does not fit")  # pruned-at-runtime path
        import time

        time.sleep(0.02 if tile == 64 else 0.001)
        return x * tile

    tuner = Autotuner(
        op,
        [Config({"tile": 64}), Config({"tile": 128}), Config({"tile": 512})],
        n_warmup=1,
        n_repeat=2,
    )
    x = jnp.ones((4, 4))
    out = tuner(x)
    best = tuner.cache[next(iter(tuner.cache))]
    assert best.kwargs["tile"] == 128
    np.testing.assert_allclose(np.asarray(out), 128.0)

    n_before = len(calls)
    tuner(x)  # cached: exactly one call, no re-bench
    assert len(calls) == n_before + 1
    # a different shape re-tunes
    tuner(jnp.ones((8, 4)))
    assert len(tuner.cache) == 2
    log = (tmp_path / "rank-0.log").read_text()
    assert "best-config" in log and "error" in log


def test_autotune_key_includes_kwargs():
    tuned_with = []

    def op(x=None, flag=False, tile=64):
        tuned_with.append((flag, tile))
        return flag

    tuner = Autotuner(
        op, [Config({"tile": 64}), Config({"tile": 128})],
        n_warmup=0, n_repeat=1,
    )
    tuner(x=jnp.ones((4, 4)), flag=False)
    tuner(x=jnp.ones((4096, 4)), flag=False)  # kw array: distinct key
    tuner(x=jnp.ones((4, 4)), flag=True)      # kw scalar: distinct key
    assert len(tuner.cache) == 3


def test_contextual_autotune_overrides_inner_tuners():
    from triton_distributed_tpu.tools.autotuner import contextual_autotune

    bench_calls = []

    def op(x, tile=64):
        bench_calls.append(tile)
        return x

    tuner = Autotuner(
        op, [Config({"tile": 64}), Config({"tile": 128})],
        n_warmup=0, n_repeat=5,
    )

    @contextual_autotune(n_repeat=1, n_warmup=0)
    def outer(x):
        return tuner(x)

    outer(jnp.ones((2, 2)))
    # 2 configs x (1 repeat + 0 warmup) + 1 replay = 3 calls, not 11.
    assert len(bench_calls) == 3
    assert outer.__name__ == "outer"  # functools.wraps applied


def test_autotune_decorator_and_all_fail():
    @autotune(configs=[{"t": 1}, {"t": 2}], n_warmup=0, n_repeat=1)
    def op(x, t=1):
        raise RuntimeError("boom")

    with pytest.raises(KernelError):
        op(jnp.ones((2, 2)))


def test_perf_model_rooflines():
    spec = ChipSpec("v5e", 197.0, 394.0, 819.0, 45.0, 4, 25.0)
    # Large square bf16 GEMM is compute-bound: time ≈ flops/peak.
    ms = estimate_gemm_time_ms(4096, 4096, 4096, jnp.bfloat16, spec)
    ideal = 2 * 4096**3 / (197e12) * 1e3
    assert ms == pytest.approx(ideal, rel=1e-6)
    # Skinny decode GEMM is memory-bound: time ≥ weight-stream time.
    ms = estimate_gemm_time_ms(1, 4096, 4096, jnp.bfloat16, spec)
    assert ms >= 2 * 4096 * 4096 / (819e9) * 1e3

    rs = estimate_reduce_scatter_time_ms(2**20, 8, spec=spec)
    ag = estimate_all_gather_time_ms(2**20, 8, spec=spec)
    ar = estimate_all_reduce_time_ms(2**20, 8, spec=spec)
    assert rs == ag and ar == pytest.approx(2 * rs)
    # Crossing a slice boundary (DCN) must cost more than staying on ICI.
    multi = estimate_reduce_scatter_time_ms(2**20, 16, 8, spec=spec)
    assert multi > rs


def test_prune_and_chip_spec_fallback():
    cfgs = [Config({"tile": t}) for t in (64, 128, 256, 512)]
    kept = prune_configs_by_model(cfgs, lambda c: abs(c.kwargs["tile"] - 256), 2)
    assert [c.kwargs["tile"] for c in kept] == [256, 128]
    assert chip_spec("TPU v5 lite").name == "v5e"
    assert chip_spec("TPU v5p").name == "v5p"
    assert chip_spec("weird device").name == "v5e"


def test_autotune_persistent_cache(tmp_path, monkeypatch):
    """A fresh Autotuner (new process stand-in) replays the argmin from
    disk without re-sweeping; a changed config space re-tunes."""
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE", "1")
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE_DIR", str(tmp_path))
    calls = []

    def op(x, tile=128):
        calls.append(tile)
        import time

        time.sleep(0.02 if tile == 64 else 0.001)
        return x * tile

    configs = [Config({"tile": 64}), Config({"tile": 128})]
    x = jnp.ones((4, 4))
    Autotuner(op, configs, n_warmup=1, n_repeat=2)(x)
    import os
    cached = os.listdir(tmp_path)
    assert len(cached) == 1 and cached[0].endswith(".json")
    swept = len(calls)
    assert swept > 2  # both configs benched

    # Fresh instance: disk hit — exactly one replay call, no sweep.
    out = Autotuner(op, configs, n_warmup=1, n_repeat=2)(x)
    assert len(calls) == swept + 1
    np.testing.assert_allclose(np.asarray(out), 128.0)

    # Config space changed: stored argmin no longer resolves → re-tune.
    calls.clear()
    Autotuner(op, [Config({"tile": 32}), Config({"tile": 256})],
              n_warmup=1, n_repeat=2)(x)
    assert len(calls) > 2


@pytest.mark.slow
def test_ag_gemm_tuned_end_to_end(ctx4, rng, tmp_path, monkeypatch):
    """The tuned overlap entry points sweep the tile grid once per shape
    and replay the argmin (in-memory + disk cache)."""
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE_DIR", str(tmp_path))
    from triton_distributed_tpu.ops.overlap import ag_gemm_tuned
    import triton_distributed_tpu.ops.overlap.tuned as tuned
    from triton_distributed_tpu.ops.overlap.tuned import _ag_tuner

    # Tiny grid: interpret-mode sweeps are slow; 2 configs prove the
    # sweep/replay machinery.
    monkeypatch.setattr(tuned, "_TILE_MS", (32,))
    monkeypatch.setattr(tuned, "_TILE_NS", (128, 256))
    _ag_tuner.cache_clear()
    M, K, N = 4 * 32, 128, 1024
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = ag_gemm_tuned(a, b, "tp", ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )
    tuner = _ag_tuner(M // 4, N // 4, K, "tp", 4, "float32", False)
    assert len(tuner.cache) == 1  # swept once, argmin cached
    out2 = ag_gemm_tuned(a, b, "tp", ctx4)  # replay path
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out), rtol=1e-6)


@pytest.mark.slow
def test_gemm_rs_tuned_end_to_end(ctx4, rng, tmp_path, monkeypatch):
    monkeypatch.setenv("TDT_AUTOTUNE_CACHE_DIR", str(tmp_path))
    from triton_distributed_tpu.ops.overlap import gemm_rs_tuned
    import triton_distributed_tpu.ops.overlap.tuned as tuned
    from triton_distributed_tpu.ops.overlap.tuned import _rs_tuner

    # Two configs so the sweep/replay path actually runs (a single
    # config short-circuits the tuner).
    monkeypatch.setattr(tuned, "_TILE_MS", (32,))
    monkeypatch.setattr(tuned, "_TILE_NS", (128, 256))
    _rs_tuner.cache_clear()
    M, K, N = 4 * 32, 256, 512
    a = jnp.asarray(rng.standard_normal((M, K), dtype=np.float32))
    b = jnp.asarray(rng.standard_normal((K, N), dtype=np.float32))
    out = gemm_rs_tuned(a, b, "tp", ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(a) @ np.asarray(b), rtol=1e-4, atol=1e-4
    )
    tuner = _rs_tuner(M, N, K // 4, "tp", 4, "float32", False)
    assert len(tuner.cache) == 1  # swept once, argmin cached


def test_anchored_spec_and_straggler_model():
    """anchored_spec derives effective rates from recorded measurements
    (hbm verbatim, MXU solved from the gemm anchor, ICI derated by the
    HBM fraction); the straggler-stall model shows the adaptive
    schedule's tolerance."""
    from triton_distributed_tpu.tools.perf_model import (
        anchored_spec,
        chip_spec,
        estimate_straggler_stall_ms,
    )

    base = chip_spec("v5e")
    anchors = {
        "chip": "v5e",
        "hbm_gbs": 667.0,
        "gemm_anchor": {"m": 8192, "n": 12288, "k": 4096, "ms": 12.65},
        "error_bars_frac": 0.3,
    }
    spec, meta = anchored_spec(anchors)
    assert meta["anchored"] is True
    assert spec.hbm_gbs == 667.0
    ideal = 2.0 * 8192 * 12288 * 4096 / (12.65e-3) / 1e12
    assert abs(spec.bf16_tflops - ideal) < 0.1
    assert abs(spec.ici_gbs_per_link - base.ici_gbs_per_link * 667 / 819) < 0.1
    # No anchors: datasheet fallback, flagged.
    spec2, meta2 = anchored_spec({})
    assert meta2 == {"anchored": False}
    assert spec2.bf16_tflops == base.bf16_tflops

    # Straggler model: lag of 3 steps at tp=8 — static exposes some,
    # adaptive exposes none (laggard met last, 7 steps of cover).
    static = estimate_straggler_stall_ms(3.0, 1.0, 8, adaptive=False)
    adapt = estimate_straggler_stall_ms(3.0, 1.0, 8, adaptive=True)
    assert adapt == 0.0
    assert static == pytest.approx(3 / 7)  # [2,1,0,...]/7
    # Lag beyond full cover exposes the remainder either way.
    assert estimate_straggler_stall_ms(10.0, 1.0, 8, True) == 3.0


def test_runtime_faults_compiles():
    """The fault-injection harness (runtime/faults.py) must
    byte-compile: its seams are imported by the pool allocator and the
    server, so a syntax error there takes down the whole serving
    stack at import time."""
    import os
    import subprocess
    import sys

    target = os.path.join(
        os.path.dirname(__file__), "..", "triton_distributed_tpu",
        "runtime", "faults.py",
    )
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", target],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"runtime/faults.py failed to compile:\n{proc.stdout}\n{proc.stderr}"
    )


def test_perf_scripts_compile():
    """Every perf/ script must at least byte-compile (tier-1 guard: the
    bench harnesses are run ad-hoc on relay windows, so a syntax error
    would otherwise surface only when a window is burning)."""
    import os
    import subprocess
    import sys

    perf_dir = os.path.join(os.path.dirname(__file__), "..", "perf")
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", perf_dir],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"perf/ scripts failed to compile:\n{proc.stdout}\n{proc.stderr}"
    )


def test_obs_modules_compile():
    """The telemetry stack must byte-compile: obs/ is imported by the
    engines, the server, the fault harness, and the profiler span
    wrapper — a syntax error there takes the whole serving stack down
    at import time. The CPU-runnable overhead bench rides along (repo
    convention: perf harnesses fail tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "obs"),
        os.path.join(root, "triton_distributed_tpu", "models", "stats.py"),
        os.path.join(root, "perf", "obs_overhead_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"obs modules failed to compile:\n{proc.stdout}\n{proc.stderr}"
    )


def test_kernel_trace_modules_compile():
    """ISSUE 8: the device task tracer's host half must byte-compile —
    obs/kernel_trace.py is imported lazily from the decode hot path
    (a traced launch decodes its ring inline), and the CPU-runnable
    bench that writes perf/MEGA_TRACE.json rides along (repo
    convention: perf harnesses fail tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "obs",
                     "kernel_trace.py"),
        os.path.join(root, "triton_distributed_tpu", "megakernel",
                     "task.py"),
        os.path.join(root, "perf", "mega_trace_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"kernel-trace modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_resident_modules_compile():
    """ISSUE-19: the resident-decode pieces must byte-compile — the
    work ring is imported lazily from the engine's mega round loop (a
    syntax error would surface mid-serve, not at import), and the
    bench that writes the resident section of perf/MEGA_SERVE.json
    rides along (repo convention: perf harnesses fail tier-1, not a
    relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "megakernel",
                     "ring.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "continuous.py"),
        os.path.join(root, "perf", "mega_serve_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"resident-decode modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_goodput_modules_compile():
    """ISSUE-13: the SLO-goodput yardstick's modules must byte-compile
    — obs/slo.py is imported by the server (a syntax error takes the
    wire down at import time), and the CPU-runnable load generator +
    goodput bench that write perf/GOODPUT.json ride along (repo
    convention: perf harnesses fail tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "obs", "slo.py"),
        os.path.join(root, "perf", "loadgen.py"),
        os.path.join(root, "perf", "goodput_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"goodput modules failed to compile:\n{proc.stdout}\n{proc.stderr}"
    )


def test_pools_modules_compile():
    """ISSUE-15: the elastic pool control plane must byte-compile —
    pools.py/autoscaler.py are imported by the serving package (a
    syntax error takes every fleet down at import time), and the
    pools bench that writes perf/POOLS.json rides along (repo
    convention: perf harnesses fail tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    serving = os.path.join(root, "triton_distributed_tpu", "serving")
    targets = [
        os.path.join(serving, "pools.py"),
        os.path.join(serving, "autoscaler.py"),
        os.path.join(serving, "router.py"),
        os.path.join(serving, "supervisor.py"),
        os.path.join(root, "perf", "pools_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"pool control-plane modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_multihost_modules_compile():
    """ISSUE-18: the multi-host launcher seam must byte-compile —
    launcher.py is imported by the supervisor (a syntax error takes
    every fleet down at import time), and the host-loss bench that
    writes perf/HOST_LOSS.json rides along (repo convention: perf
    harnesses fail tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    serving = os.path.join(root, "triton_distributed_tpu", "serving")
    targets = [
        os.path.join(serving, "launcher.py"),
        os.path.join(serving, "supervisor.py"),
        os.path.join(serving, "remote.py"),
        os.path.join(serving, "run_server.py"),
        os.path.join(root, "perf", "host_loss_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"multi-host modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_tier1_marker_audit():
    """ISSUE 8 satellite: the tier-1 window is spent by conftest's
    ``_FILE_ORDER`` schedule — audit it against reality so new trace
    tests actually run inside the wall clock: every listed file must
    exist (a stale entry silently reorders nothing), and the device-
    tracer suite must both be scheduled ahead of the multi-minute tail
    AND carry runnable (non-slow) tests."""
    import ast
    import os

    import conftest

    tests_dir = os.path.dirname(__file__)
    actual = {f for f in os.listdir(tests_dir)
              if f.startswith("test_") and f.endswith(".py")}
    stale = [f for f in conftest._FILE_ORDER if f not in actual]
    assert not stale, f"conftest._FILE_ORDER lists missing files: {stale}"

    def fast_tests(fname):
        """Non-slow test function names of one suite file — THE fast-
        test detector every per-suite audit below shares (a fix to
        the decorator check must not need N coordinated edits)."""
        src = open(os.path.join(tests_dir, fname)).read()
        return [
            n.name for n in ast.walk(ast.parse(src))
            if isinstance(n, ast.FunctionDef)
            and n.name.startswith("test_")
            and not any("slow" in ast.dump(d) for d in n.decorator_list)
        ]
    # The trace suite is explicitly scheduled (not just rank -1) and
    # sits before the interpret-heavy tail.
    order = conftest._FILE_ORDER
    assert "test_kernel_trace.py" in order
    assert (order.index("test_kernel_trace.py")
            < order.index("test_serving.py"))
    # ISSUE-9: the process-fleet chaos suite spawns child interpreters
    # (~seconds per fleet) — it must be explicitly scheduled (not
    # rank -1 ahead of everything) AND sit before the multi-minute
    # interpret tail so the wall clock actually reaches it.
    assert "test_fleet.py" in order
    assert (order.index("test_router.py")
            < order.index("test_fleet.py")
            < order.index("test_serving.py"))
    # ISSUE-10: the slot-migration suite (tiny-model bit-exactness +
    # stub fleets) rides right behind the fleet suite, still ahead of
    # the interpret tail, and must carry tier-1-runnable tests.
    assert "test_migration.py" in order
    assert (order.index("test_fleet.py")
            < order.index("test_migration.py")
            < order.index("test_serving.py"))
    mig_fast = fast_tests("test_migration.py")
    assert len(mig_fast) >= 5, (
        f"slot-migration suite has too few tier-1-runnable tests: "
        f"{mig_fast}"
    )
    # ISSUE-12: the durable-KV-tier suite (pure store + tiny-model
    # spill/fault-back + the supervisor-restart resume case) rides
    # right behind the migration suite, ahead of the interpret tail,
    # and must carry tier-1-runnable tests — containment regressions
    # have to FAIL tier-1, not wait for a relay window.
    assert "test_kv_tier.py" in order
    assert (order.index("test_migration.py")
            < order.index("test_kv_tier.py")
            < order.index("test_serving.py"))
    tier_fast = fast_tests("test_kv_tier.py")
    assert len(tier_fast) >= 5, (
        f"KV-tier suite has too few tier-1-runnable tests: {tier_fast}"
    )
    # ISSUE-17: the KV-fabric suite (wire tier verbs, peer fault-back
    # bit-exactness, chaos degradation, tier-aware placement) rides
    # right behind the KV-tier suite it extends, ahead of the
    # interpret tail, and must carry tier-1-runnable tests — a
    # wrong-bits-from-a-peer regression has to FAIL tier-1.
    assert "test_kv_fabric.py" in order
    assert (order.index("test_kv_tier.py")
            < order.index("test_kv_fabric.py")
            < order.index("test_serving.py"))
    fabric_fast = fast_tests("test_kv_fabric.py")
    assert len(fabric_fast) >= 5, (
        f"KV-fabric suite has too few tier-1-runnable tests: "
        f"{fabric_fast}"
    )
    # ISSUE-13: the SLO-goodput suite (streaming wire grammar, cancel
    # teardown, loadgen determinism, fleet-scope scrape) rides with
    # the fleet-family suites — streaming/cancel regressions must
    # FAIL tier-1, not wait for a goodput_bench run.
    assert "test_goodput.py" in order
    assert (order.index("test_kv_tier.py")
            < order.index("test_goodput.py")
            < order.index("test_serving.py"))
    gp_fast = fast_tests("test_goodput.py")
    assert len(gp_fast) >= 5, (
        f"SLO-goodput suite has too few tier-1-runnable tests: {gp_fast}"
    )
    # ISSUE-15: the elastic-pools suite (role scoring, scheduler
    # waves/shedding, autoscaler control loop on a fake fleet, pools
    # routing, batched handoff export) rides right behind the goodput
    # suite, ahead of the interpret tail, and must carry tier-1-
    # runnable tests — control-plane regressions have to FAIL tier-1,
    # not wait for a pools_bench run.
    assert "test_pools.py" in order
    assert (order.index("test_goodput.py")
            < order.index("test_pools.py")
            < order.index("test_serving.py"))
    pool_fast = fast_tests("test_pools.py")
    assert len(pool_fast) >= 5, (
        f"elastic-pools suite has too few tier-1-runnable tests: "
        f"{pool_fast}"
    )
    # ISSUE-18: the multi-host suite (launcher contracts, host failure
    # domains, epoch fencing, spawn failover) rides right behind the
    # pools suite, ahead of the interpret tail, and must carry tier-1-
    # runnable tests — a fencing or correlated-classification
    # regression has to FAIL tier-1, not wait for a host_loss_bench
    # run.
    assert "test_multihost.py" in order
    assert (order.index("test_pools.py")
            < order.index("test_multihost.py")
            < order.index("test_serving.py"))
    mh_fast = fast_tests("test_multihost.py")
    assert len(mh_fast) >= 5, (
        f"multi-host suite has too few tier-1-runnable tests: "
        f"{mh_fast}"
    )
    # ISSUE-20: the long-context suite (cp-prefill bit-exactness +
    # ring validation, sharded-slot decode/tier paging, gather-stitch
    # snapshot round-trip, bf16/int8 kernel parity, document loadgen
    # class) rides with the fleet-family suites, ahead of the
    # interpret tail, and must carry tier-1-runnable tests — a
    # sharded-decode or exchange-schedule regression has to FAIL
    # tier-1, not wait for a long_context_bench run.
    assert "test_long_context.py" in order
    assert (order.index("test_kv_tier.py")
            < order.index("test_long_context.py")
            < order.index("test_serving.py"))
    lc_fast = fast_tests("test_long_context.py")
    assert len(lc_fast) >= 5, (
        f"long-context suite has too few tier-1-runnable tests: "
        f"{lc_fast}"
    )
    # ISSUE-16: the tree-speculation suite rides right behind the
    # linear-speculation suite (shared tiny-model jit warmup), ahead of
    # the interpret tail, and must carry tier-1-runnable tests — a
    # tree-verify exactness regression has to FAIL tier-1, not wait
    # for a spec_decode_bench run.
    assert "test_tree_spec.py" in order
    assert (order.index("test_speculative.py")
            < order.index("test_tree_spec.py")
            < order.index("test_serving.py"))
    tree_fast = fast_tests("test_tree_spec.py")
    assert len(tree_fast) >= 5, (
        f"tree-speculation suite has too few tier-1-runnable tests: "
        f"{tree_fast}"
    )
    # ISSUE-11: the MoE serving suite sits with the mega-family suites
    # (after the tracer suite, before the interpret-heavy tail) and
    # must carry tier-1-runnable tests — the MoE fast path has to FAIL
    # tier-1 when broken, not wait for the post-tail test_moe.py.
    assert "test_moe_serving.py" in order
    assert (order.index("test_kernel_trace.py")
            < order.index("test_moe_serving.py")
            < order.index("test_serving.py"))
    moe_fast = fast_tests("test_moe_serving.py")
    assert len(moe_fast) >= 5, (
        f"MoE serving suite has too few tier-1-runnable tests: "
        f"{moe_fast}"
    )
    # And it contains non-slow tests, so tier-1 (which skips `slow`)
    # actually exercises the tracer.
    kt_fast = fast_tests("test_kernel_trace.py")
    assert len(kt_fast) >= 5, (
        f"device-tracer suite has too few tier-1-runnable tests: "
        f"{kt_fast}"
    )
    # ISSUE-19: the resident-decode suite (work-ring protocol, doorbell
    # validation, metric pre-touch, CLI refusal wording, knob guards)
    # rides right behind the tracer suite whose validate_ring it
    # extends, ahead of the interpret tail, and must carry tier-1-
    # runnable tests — a ring-desync or fallback regression has to
    # FAIL tier-1, not wait for a mega_serve_bench run.
    assert "test_resident.py" in order
    assert (order.index("test_kernel_trace.py")
            < order.index("test_resident.py")
            < order.index("test_serving.py"))
    res_fast = fast_tests("test_resident.py")
    assert len(res_fast) >= 5, (
        f"resident-decode suite has too few tier-1-runnable tests: "
        f"{res_fast}"
    )


def test_long_context_modules_compile():
    """ISSUE-20: the long-context serving stack must byte-compile —
    long_context.py/slot_state.py/continuous.py are imported by the
    engine's admission path (a syntax error takes serving down at
    import time), the cp/sharded attention substrate rides in ops and
    layers, and the bench that writes perf/LONG_CONTEXT.json rides
    along (repo convention: perf harnesses fail tier-1, not a relay
    window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    pkg = os.path.join(root, "triton_distributed_tpu")
    targets = [
        os.path.join(pkg, "models", "long_context.py"),
        os.path.join(pkg, "models", "continuous.py"),
        os.path.join(pkg, "models", "slot_state.py"),
        os.path.join(pkg, "models", "qwen.py"),
        os.path.join(pkg, "layers", "tp_attn.py"),
        os.path.join(pkg, "ops", "attention", "ring_attention.py"),
        os.path.join(pkg, "ops", "attention", "flash_decode.py"),
        os.path.join(root, "perf", "loadgen.py"),
        os.path.join(root, "perf", "long_context_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"long-context modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_serving_tier_modules_compile():
    """The multi-engine serving tier must byte-compile: the router,
    replica, and process-fleet modules are imported by the serving
    package (so a syntax error takes the whole server down at import
    time), and the CPU-runnable benches that write perf/ROUTER.json
    and perf/FLEET.json ride along (repo convention: perf harnesses
    fail tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "serving",
                     "router.py"),
        os.path.join(root, "triton_distributed_tpu", "serving",
                     "replica.py"),
        os.path.join(root, "triton_distributed_tpu", "serving",
                     "remote.py"),
        os.path.join(root, "triton_distributed_tpu", "serving",
                     "supervisor.py"),
        os.path.join(root, "triton_distributed_tpu", "serving",
                     "run_server.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "stub.py"),
        os.path.join(root, "perf", "router_bench.py"),
        os.path.join(root, "perf", "fleet_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"serving-tier modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_migration_modules_compile():
    """ISSUE-10: the slot-migration stack must byte-compile — the
    portable-slot-state module is imported by the continuous engine's
    admission path (a syntax error takes serving down at import time),
    and the CPU-runnable bench that writes perf/MIGRATION.json rides
    along (repo convention: perf harnesses fail tier-1, not a relay
    window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "models",
                     "slot_state.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "continuous.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "stub.py"),
        os.path.join(root, "perf", "migration_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"slot-migration modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_kv_quant_modules_compile():
    """The quantized-KV stack must byte-compile: the scale-aware pool,
    the dequantizing attention kernels, and the CPU-runnable bench that
    writes perf/KV_QUANT.json (run ad-hoc like the other perf
    harnesses — a syntax error must fail tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "models",
                     "paged_kv_cache.py"),
        os.path.join(root, "triton_distributed_tpu", "ops", "attention",
                     "flash_decode.py"),
        os.path.join(root, "triton_distributed_tpu", "ops", "attention",
                     "flash_attention.py"),
        os.path.join(root, "perf", "kv_quant_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"kv-quant modules failed to compile:\n{proc.stdout}\n{proc.stderr}"
    )


def test_mega_serve_modules_compile():
    """The megakernel serving fast path must byte-compile: the fused
    int8/sampling/overlap decode modules are imported by both engines
    (a syntax error takes serving down at import time), and the
    CPU-runnable bench that writes perf/MEGA_SERVE.json rides along
    (repo convention: perf harnesses fail tier-1, not a relay
    window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "megakernel"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "continuous.py"),
        os.path.join(root, "triton_distributed_tpu", "runtime",
                     "jax_compat.py"),
        os.path.join(root, "perf", "mega_serve_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"mega-serve modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_moe_serving_modules_compile():
    """ISSUE-11: the MoE serving fast path must byte-compile — the
    routed-expert model/layer/ops stack, the megakernel's MoE task
    modules, and the CPU-runnable bench that writes
    perf/MOE_SERVE.json (repo convention: perf harnesses fail tier-1,
    not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "models",
                     "qwen_moe.py"),
        os.path.join(root, "triton_distributed_tpu", "layers",
                     "tp_moe.py"),
        os.path.join(root, "triton_distributed_tpu", "ops", "moe"),
        os.path.join(root, "triton_distributed_tpu", "megakernel"),
        os.path.join(root, "perf", "moe_serve_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"MoE serving modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_kv_tier_modules_compile():
    """ISSUE-12: the durable KV tier must byte-compile — the PageStore
    subsystem, the tier-aware prefix cache / continuous engine /
    supervisor wiring, and the CPU-runnable bench that writes
    perf/KV_TIER.json (repo convention: perf harnesses fail tier-1,
    not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "models",
                     "kv_tier.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "prefix_cache.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "continuous.py"),
        os.path.join(root, "triton_distributed_tpu", "serving",
                     "supervisor.py"),
        os.path.join(root, "perf", "kv_tier_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"KV tier modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_kv_fabric_modules_compile():
    """ISSUE-17: the KV fabric must byte-compile — the fabric client /
    wire peers (kv_tier.py), the suite itself, and the CPU-runnable
    bench that writes perf/KV_FABRIC.json (repo convention: perf
    harnesses fail tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "models",
                     "kv_tier.py"),
        os.path.join(root, "tests", "test_kv_fabric.py"),
        os.path.join(root, "perf", "kv_fabric_bench.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"KV fabric modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_tree_speculation_modules_compile():
    """ISSUE-16: every layer the tree-speculation path threads through
    must byte-compile — the drafter/verifier, the radix proposer, the
    row-move commit, the biased flash kernel and its model plumbing,
    both engines, and the CPU-runnable bench that writes
    perf/SPEC_DECODE.json (repo convention: perf harnesses fail
    tier-1, not a relay window)."""
    import os
    import subprocess
    import sys

    root = os.path.join(os.path.dirname(__file__), "..")
    targets = [
        os.path.join(root, "triton_distributed_tpu", "models",
                     "speculative.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "prefix_cache.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "paged_kv_cache.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "qwen.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "engine.py"),
        os.path.join(root, "triton_distributed_tpu", "models",
                     "continuous.py"),
        os.path.join(root, "triton_distributed_tpu", "layers",
                     "tp_attn.py"),
        os.path.join(root, "triton_distributed_tpu", "ops", "attention",
                     "flash_attention.py"),
        os.path.join(root, "perf", "spec_decode_bench.py"),
        os.path.join(root, "perf", "loadgen.py"),
    ]
    proc = subprocess.run(
        [sys.executable, "-m", "compileall", "-q", "-f", *targets],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, (
        f"tree-speculation modules failed to compile:\n"
        f"{proc.stdout}\n{proc.stderr}"
    )


def test_serving_cli_speculative_mega_conflict(capsys):
    """Both serving CLIs refuse --speculative with --mode mega by flag
    name, BEFORE loading a model (argparse error → SystemExit 2) — for
    EVERY --model spelling, including the ones whose name resolution
    used to run first and die on a missing checkpoint instead of the
    named-flag message (ISSUE-16 satellite). The refusal text names
    the actual conflicting pair. The spec-string parser round-trips
    the new overlap_ar field."""
    import os
    import sys

    import pytest

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from perf import serve_demo
    from triton_distributed_tpu.serving import run_server

    for main in (serve_demo.main, run_server.main):
        for extra in ([], ["--model", "moe"], ["--model", "stub"]):
            with pytest.raises(SystemExit) as ei:
                main([*extra, "--speculative", "2", "--mode", "mega"])
            assert ei.value.code == 2  # argparse p.error exit code
            err = capsys.readouterr().err
            assert "--speculative and --mode mega" in err, err

    from triton_distributed_tpu.megakernel.code_generator import MegaConfig

    cfg = MegaConfig(tile_n=512, nbuf=3, fuse_norms=True,
                     cross_prefetch=True, overlap_ar=True)
    assert MegaConfig.from_spec(cfg.spec()) == cfg
    # Old 5-field strings (pre-overlap_ar MEGA_TUNED.json) still parse.
    old = MegaConfig.from_spec("1024:1024:2:1:0")
    assert old.overlap_ar is False and old.fuse_norms is True


def test_serving_cli_tier_flags_require_continuous_stack():
    """Both serving CLIs refuse --tier-bytes/--tier-dir on paths that
    would silently ignore them (the plain fixed-batch Engine, the
    single stub server) by flag name, BEFORE loading a model — the
    speculative×mega fail-fast convention (docs/serving.md 'Tiered
    KV')."""
    import os
    import sys

    import pytest

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from perf import serve_demo
    from triton_distributed_tpu.serving import run_server

    for main in (serve_demo.main, run_server.main):
        for flags in (["--tier-bytes", "1048576"],
                      ["--tier-dir", "/tmp/nope.tier"]):
            with pytest.raises(SystemExit) as ei:
                main(flags)
            assert ei.value.code == 2  # argparse p.error exit code
    # The single-stub server has no tier either (fleet stub children
    # ride the supervisor's resume_dir instead).
    with pytest.raises(SystemExit) as ei:
        run_server.main(["--model", "stub", "--tier-bytes", "1048576"])
    assert ei.value.code == 2

def test_serving_cli_tier_shared_guardrails(capsys):
    """Both serving CLIs refuse every --tier-shared combination that
    would silently do nothing (single engine, stub fleet, process
    fleet without a common dir, threaded replicas without a tier) by
    flag name, BEFORE loading a model — the PR 12 tier-flag
    convention (docs/scale-out.md 'KV fabric')."""
    import os
    import sys

    import pytest

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from perf import serve_demo
    from triton_distributed_tpu.serving import run_server

    cases = (
        # One engine: nothing to share.
        ["--tier-shared", "--tier-bytes", "1048576"],
        # Stub fleet children have no KV tier at all.
        ["--model", "stub", "--fleet", "2", "--tier-shared"],
        # Separate processes share through DISK: --tier-dir required.
        ["--fleet", "2", "--tier-shared", "--tier-bytes", "1048576"],
        # Threaded replicas still need a tier to share.
        ["--replicas", "2", "--tier-shared"],
    )
    for main in (serve_demo.main, run_server.main):
        for flags in cases:
            with pytest.raises(SystemExit) as ei:
                main(flags)
            assert ei.value.code == 2, flags  # argparse p.error
            err = capsys.readouterr().err
            assert "--tier-shared" in err, (flags, err)
