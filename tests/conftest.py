"""Test configuration: force an 8-virtual-device CPU mesh.

The test strategy (SURVEY.md §4) improves on the reference's
torchrun-on-real-GPUs scripts: JAX simulates an 8-device mesh on CPU
(``--xla_force_host_platform_device_count``) and Pallas TPU interpret mode
(``pltpu.InterpretParams``) executes kernels — including inter-chip remote
DMAs and semaphores — with faithful TPU memory semantics. Unit and
multi-"node" tests therefore run cluster-free.

Note: the environment's sitecustomize imports jax at interpreter startup and
pins ``jax_platforms`` to the TPU plugin, so plain env vars are ignored; we
override via ``jax.config`` before any backend is instantiated.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# Keep the autotuner's persistent cache out of ~/.cache during tests;
# the persistence test opts back in with a tmp_path dir.
os.environ.setdefault("TDT_AUTOTUNE_CACHE", "0")

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

import numpy as np
import pytest

from triton_distributed_tpu.runtime import mesh as mesh_mod


@pytest.fixture
def ctx8():
    """8-device single-axis tp mesh."""
    ctx = mesh_mod.initialize_distributed(tp=8)
    yield ctx
    mesh_mod.finalize_distributed()


@pytest.fixture
def ctx4():
    """4-device single-axis tp mesh."""
    ctx = mesh_mod.initialize_distributed(tp=4, devices=jax.devices()[:4])
    yield ctx
    mesh_mod.finalize_distributed()


@pytest.fixture
def ctx2x4():
    """2x4 dp×tp mesh."""
    ctx = mesh_mod.initialize_distributed(dp=2, tp=4)
    yield ctx
    mesh_mod.finalize_distributed()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def fresh_telemetry():
    """Opt-in: enable and zero the process-global metrics registry and
    event ring around one test, restoring the prior enabled state.
    Tests asserting ABSOLUTE counter/event totals need it — engines
    emit into the process globals from any test in the suite. The ONE
    reset protocol; tests/test_obs.py makes it autouse file-wide."""
    from triton_distributed_tpu import obs
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.obs import metrics as obs_metrics

    prev = obs.is_enabled()
    obs.set_enabled(True)
    obs_metrics.default_registry().clear()
    obs_events.default_ring().clear()
    yield
    obs.set_enabled(prev)


@pytest.fixture(autouse=True)
def _audit_serving_pools():
    """Pool/radix invariant audit after EVERY test (docs/serving.md
    "Fault tolerance"): any engine or prefix tree the test touched must
    end with free list ∪ slot pages ∪ tree pages partitioning the pool
    exactly — a leak fails the test that caused it, not a later one.
    Tests that never import the serving stack pay a dict lookup."""
    yield
    import sys

    problems = []
    cont = sys.modules.get("triton_distributed_tpu.models.continuous")
    if cont is not None:
        for eng in list(cont.ContinuousEngine._live):
            problems += [f"ContinuousEngine: {p}" for p in eng.audit()]
    engmod = sys.modules.get("triton_distributed_tpu.models.engine")
    if engmod is not None:
        for eng in list(engmod.Engine._live):
            problems += [f"Engine: {p}" for p in eng.audit()]
    pcmod = sys.modules.get("triton_distributed_tpu.models.prefix_cache")
    if pcmod is not None:
        for tree in list(pcmod.PrefixCache._live):
            problems += [f"PrefixCache: {p}" for p in tree.audit()]
    assert not problems, (
        "pool/radix audit failed after test: " + "; ".join(problems)
    )


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight interpret-mode runs; excluded from the default "
        "suite (VERDICT r2 weak #7 — keep a fast path on one core). "
        "Run with `-m slow` or TDT_RUN_SLOW=1 (an empty -m '' is "
        "indistinguishable from no -m and still skips).",
    )


# Tier-1 runs under a hard wall-clock budget (ROADMAP.md: 870 s), and
# the FULL fast suite no longer fits it on this one-core interpret
# host — so spend the window highest-yield-first: cheap/high-signal
# suites up front, the multi-minute interpret-heavy suites (and the
# families that cannot execute under this container's 0.4.x interpret
# gaps — collectives/overlap/stress, see runtime/jax_compat.py) at the
# back. Within-file order is preserved (stable sort), every test still
# runs when the clock allows, and the order is deterministic. Ordered
# by measured ascending cost-per-verified-test on this host. Files NOT
# in the list sort FIRST (rank -1): a new test file must never be
# silently starved behind the multi-minute tail — if it turns out
# expensive, add it here explicitly.
_FILE_ORDER = [
    "test_tools.py", "test_bench_tuning.py", "test_onchip_queue.py",
    "test_runtime.py", "test_sampling.py", "test_language.py",
    "test_layers.py", "test_native.py", "test_obs.py", "test_router.py",
    "test_fleet.py", "test_migration.py", "test_kv_tier.py",
    "test_kv_fabric.py", "test_goodput.py", "test_pools.py",
    "test_multihost.py", "test_long_context.py",
    "test_attention.py", "test_p2p.py", "test_kv_quant.py",
    "test_speculative.py", "test_tree_spec.py", "test_kernel_trace.py",
    "test_resident.py",
    "test_moe_serving.py", "test_megakernel.py",
    "test_tpu_lowering.py",
    "test_prefix_cache.py", "test_faults.py", "test_serving.py",
    "test_model.py", "test_collectives.py", "test_sp_attention.py",
    "test_moe.py", "test_stress.py", "test_overlap.py",
]
_FILE_RANK = {name: i for i, name in enumerate(_FILE_ORDER)}


def pytest_collection_modifyitems(config, items):
    items.sort(
        key=lambda item: _FILE_RANK.get(
            os.path.basename(str(item.fspath)), -1
        )
    )
    if config.option.markexpr or os.environ.get("TDT_RUN_SLOW") == "1":
        return
    skip = pytest.mark.skip(
        reason="slow (opt in: -m slow or TDT_RUN_SLOW=1)"
    )
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
