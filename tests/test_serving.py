"""Model-server tests: protocol round trip vs direct Engine output.

Parity model: the reference's server is exercised by its chat/bench
clients (``mega_triton_kernel/test/models/``); here the client is
in-process and the golden is ``Engine.serve`` on the same weights.
"""

import numpy as np
import pytest

from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.serving import ModelServer, request


def test_server_round_trip(ctx4):
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    engine = Engine(model, temperature=0.0, mode="xla")

    prompts = np.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], np.int32)
    gold = engine.serve(prompts, gen_len=4)

    server = ModelServer(engine).start()
    try:
        assert request(server.host, server.port, {"cmd": "ping"})["ok"]
        resp = request(
            server.host, server.port,
            {"input_ids": prompts.tolist(), "gen_len": 4},
        )
        np.testing.assert_array_equal(
            np.asarray(resp["output_ids"], np.int32), gold
        )
        assert "decode_ms_per_step" in resp["stats"]
    finally:
        server.shutdown()


def test_server_reports_errors(ctx4):
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    engine = Engine(model, mode="xla")
    server = ModelServer(engine).start()
    try:
        import pytest

        # Indivisible prompt lengths are auto-padded now — serve works.
        resp = request(
            server.host, server.port,
            {"input_ids": [[1, 2, 3]], "gen_len": 2},  # len 3 % tp4 != 0
        )
        assert np.asarray(resp["output_ids"]).shape == (1, 5)

        # A malformed request still surfaces as a server error.
        with pytest.raises(RuntimeError, match="server error"):
            request(
                server.host, server.port,
                {"input_ids": [[1, 2, 3]], "gen_len": 2,
                 "prompt_start": [7]},  # out of range for s=3
            )
    finally:
        server.shutdown()


def test_continuous_batching(ctx4):
    """Admission/eviction over the paged pool: mixed-length requests,
    fewer slots than requests, outputs match per-request dense goldens
    and every pool page is released at the end."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    prompts = [
        np.asarray([5, 9, 2, 4], np.int32),
        np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32),
        np.asarray([11, 12, 13, 14], np.int32),
    ]
    gens = [5, 3, 4]

    # Goldens: the plain dense engine, one request at a time.
    golds = []
    for p, g in zip(prompts, gens):
        out = Engine(model, temperature=0.0).serve(p[None], gen_len=g)
        golds.append(out[0, len(p):])

    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64
    )
    free0 = len(eng.pool.free)
    outs = eng.run(list(zip(prompts, gens)))
    for got, gold in zip(outs, golds):
        np.testing.assert_array_equal(got, np.asarray(gold))
    assert len(eng.pool.free) == free0  # all pages released


def test_continuous_batching_eos(ctx4):
    """A request stopping at eos releases its slot early; the freed
    pages admit the waiting request."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    p = np.asarray([5, 9, 2, 4], np.int32)
    # Find what the model actually emits so we can use it as "eos".
    probe = Engine(model, temperature=0.0).serve(p[None], gen_len=3)[0, 4:]
    eos = int(probe[1])  # second generated token

    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64, eos_id=eos
    )
    outs = eng.run([(p, 6), (p, 2)])
    # Request 0 stops right after emitting eos (2 tokens, not 6).
    np.testing.assert_array_equal(outs[0], probe[:2])
    assert len(outs[1]) == 2


def test_continuous_batching_oversubscribed_pool(ctx4):
    """num_pages below max_batch*pages_per_seq (the point of paging):
    requests wait for pages, outputs stay correct, capacity errors are
    loud."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    p = np.asarray([5, 9, 2, 4], np.int32)
    gold = Engine(model, temperature=0.0).serve(p[None], gen_len=4)[0, 4:]

    # 2 slots but only one sequence's worth of pages: strictly serial.
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, num_pages=4
    )
    outs = eng.run([(p, 4), (p, 4)])
    for got in outs:
        np.testing.assert_array_equal(got, np.asarray(gold))

    import pytest

    small = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64, num_pages=3
    )
    with pytest.raises(ValueError, match="unservable"):
        # Needs 4 pages; capacity is 3.
        small.run([(np.zeros(48, np.int32), 16)])


@pytest.mark.slow
def test_continuous_batching_mega_multi(ctx4):
    """mode="mega" continuous serving decodes in NS-token chunks
    (paged multi-step launches) with host admission at chunk
    boundaries; outputs must match the dense per-request goldens."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    prompts = [
        np.asarray([5, 9, 2, 4], np.int32),
        np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32),
        np.asarray([11, 12, 13, 14], np.int32),
    ]
    gens = [5, 3, 4]
    golds = []
    for p, g in zip(prompts, gens):
        out = Engine(model, temperature=0.0).serve(p[None], gen_len=g)
        golds.append(out[0, len(p):])

    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, mode="mega"
    )
    free0 = len(eng.pool.free)
    outs = eng.run(list(zip(prompts, gens)))
    for got, gold in zip(outs, golds):
        np.testing.assert_array_equal(got, np.asarray(gold))
    assert len(eng.pool.free) == free0  # all pages released


@pytest.mark.slow
def test_continuous_batching_mega_eos(ctx4):
    """eos mid-chunk: overshoot tokens are discarded, the slot frees at
    the chunk boundary, and the queued request still serves right."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    p = np.asarray([5, 9, 2, 4], np.int32)
    probe = Engine(model, temperature=0.0).serve(p[None], gen_len=3)[0, 4:]
    eos = int(probe[1])

    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64, eos_id=eos,
        mode="mega",
    )
    outs = eng.run([(p, 6), (p, 2)])
    np.testing.assert_array_equal(outs[0], probe[:2])
    assert len(outs[1]) == 2


def _mega_compose_engine(model, mode, **kw):
    """The full serving composition the PR 7 fast path must carry:
    int8 pool + radix prefix cache + chunked prefill admission."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    return ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, mode=mode,
        kv_dtype="int8", prefix_cache=True, prefill_chunk=16, **kw
    )


_COMPOSE_PROMPTS = [
    np.asarray([5, 9, 2, 4], np.int32),
    np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32),
    np.asarray([5, 9, 2, 4, 11, 12], np.int32),  # shares a prefix
]
_COMPOSE_GENS = [5, 3, 4]


@pytest.mark.slow
def test_continuous_mega_int8_compose_greedy(ctx4):
    """The tentpole gate: mode='mega' with the REAL serving
    configuration (int8 pool + prefix cache + chunked prefill, prefix
    reuse across retirements included) emits exactly the unfused int8
    engine's greedy tokens — in-kernel dequant, full-precision launch
    band, sequential append scatter, and overshoot trash-routing all
    compose without changing a single token on this workload."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    golds = _mega_compose_engine(model, "xla").run(
        list(zip(_COMPOSE_PROMPTS, _COMPOSE_GENS))
    )
    eng = _mega_compose_engine(model, "mega")
    free0 = len(eng.pool.free)
    outs = eng.run(list(zip(_COMPOSE_PROMPTS, _COMPOSE_GENS)))
    for got, gold in zip(outs, golds):
        np.testing.assert_array_equal(got, np.asarray(gold))
    st = eng.last_stats
    assert st["mega_launches"] > 0
    assert st["kv_dtype"] == "int8"
    # Pages back in the pool or retained by the radix tree — audited by
    # the autouse fixture; here just prove nothing leaked outright.
    assert len(eng.pool.free) + eng.prefix.node_count == free0


@pytest.mark.slow
def test_continuous_mega_sampled_seeded(ctx4):
    """Per-slot temperature sampling INSIDE the fused launch: seeded
    runs are reproducible, launches actually happen (no silent
    fallback), outputs differ from greedy, and a mixed greedy/sampled
    batch (per-request temperature=0 override) still launches fused
    with the greedy slot emitting the greedy chain."""
    from triton_distributed_tpu.models.continuous import Request

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)

    def sampled_run(seed):
        eng = _mega_compose_engine(model, "mega", temperature=0.9,
                                   seed=seed)
        outs = eng.run(list(zip(_COMPOSE_PROMPTS, _COMPOSE_GENS)))
        return outs, eng.last_stats

    o1, st1 = sampled_run(3)
    o2, _ = sampled_run(3)
    for a, b in zip(o1, o2):
        np.testing.assert_array_equal(a, b)
    assert st1["mega_launches"] > 0
    assert st1["mega_fallback_steps"] == 0
    greedy = _mega_compose_engine(model, "mega").run(
        list(zip(_COMPOSE_PROMPTS, _COMPOSE_GENS))
    )
    assert any(
        not np.array_equal(a, g) for a, g in zip(o1, greedy)
    )
    # Mixed batch: slot-level greedy override rides the sampled launch.
    mixed_eng = _mega_compose_engine(model, "mega", temperature=0.9,
                                     seed=3)
    reqs = [
        Request(_COMPOSE_PROMPTS[0], _COMPOSE_GENS[0], temperature=0.0),
        Request(_COMPOSE_PROMPTS[1], _COMPOSE_GENS[1]),
    ]
    mixed = mixed_eng.run(reqs, results=True)
    assert mixed_eng.last_stats["mega_launches"] > 0
    greedy_solo = _mega_compose_engine(model, "mega").run(
        [(_COMPOSE_PROMPTS[0], _COMPOSE_GENS[0])]
    )
    np.testing.assert_array_equal(mixed[0].tokens, greedy_solo[0])


@pytest.mark.slow
def test_continuous_mega_filtered_sampling_falls_back(ctx4):
    """top-k/top-p slots can't ride the in-kernel Gumbel argmax (it
    samples the unfiltered temperature distribution): those rounds fall
    back to single-step decode with host-side filtered sampling, and
    the fallback counter says so."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = _mega_compose_engine(model, "mega", temperature=0.9,
                               top_p=0.8, seed=3)
    outs = eng.run(list(zip(_COMPOSE_PROMPTS[:2], _COMPOSE_GENS[:2])))
    st = eng.last_stats
    assert st["mega_launches"] == 0
    assert st["mega_fallback_steps"] > 0
    assert all(len(o) == g for o, g in zip(outs, _COMPOSE_GENS))


@pytest.mark.slow
def test_continuous_mega_tail_and_overshoot(ctx4):
    """Mega tail paths: a row within NS of max_length single-steps its
    tail (fallback counter), and a row finishing mid-launch discards
    its overshoot tokens with the overshoot KV trash-routed — pool and
    tree stay clean (autouse audit), tokens match the unfused engine."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    # 52-token prompt + 12 = 64 == max_length: the last rounds sit
    # within NS of capacity and must fall back.
    p_long = np.arange(1, 53, dtype=np.int32)
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    def run(mode):
        eng = ContinuousEngine(
            model, max_batch=1, page_size=16, max_length=64, mode=mode,
            kv_dtype="int8",
        )
        return eng.run([(p_long, 12)]), eng.last_stats

    (gold,), _ = run("xla")
    (got,), st = run("mega")
    np.testing.assert_array_equal(got, gold)
    assert st["mega_fallback_steps"] > 0
    # Overshoot: gen_len 2 finishes on the first launch (NS=8); the 6
    # overshoot tokens are discarded and their KV trash-routed.
    eng = _mega_compose_engine(model, "mega")
    outs = eng.run([(np.asarray([5, 9, 2, 4], np.int32), 2)])
    assert len(outs[0]) == 2
    assert eng.last_stats["mega_launches"] == 1


@pytest.mark.slow
def test_continuous_mega_telemetry(ctx4):
    """tdt_mega_* telemetry: launch counter and NS-amortization gauge
    mirror ``last_stats`` through the registry, and ``mega:launch``
    events land in the ring."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.obs import metrics as obs_metrics

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    since = obs_events.default_ring().next_seq
    eng = _mega_compose_engine(model, "mega")
    eng.run(list(zip(_COMPOSE_PROMPTS[:2], _COMPOSE_GENS[:2])))
    st = eng.last_stats
    snap = obs_metrics.default_registry().snapshot()
    assert snap["tdt_mega_launches_total"]["series"][0]["value"] >= (
        st["mega_launches"]
    )
    gauge = snap["tdt_mega_ns_amortization"]["series"][0]["value"]
    assert gauge == pytest.approx(
        st["decode_steps"] / max(st["mega_launches"], 1)
    )
    events, _dropped = obs_events.default_ring().tail(since)
    kinds = [e.kind for e in events]
    assert kinds.count("mega:launch") == st["mega_launches"]


def test_continuous_batching_first_token_finishes(ctx4):
    """gen_len=1 and first-token-eos requests complete at admission:
    exactly one token back, and the freed slot admits the next request
    immediately."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    p = np.asarray([5, 9, 2, 4], np.int32)
    first = int(
        Engine(model, temperature=0.0).serve(p[None], gen_len=1)[0, 4]
    )

    eng = ContinuousEngine(model, max_batch=1, page_size=16, max_length=64)
    outs = eng.run([(p, 1), (p, 2)])
    assert len(outs[0]) == 1 and int(outs[0][0]) == first
    assert len(outs[1]) == 2

    # eos as the very first sampled token.
    eng2 = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64, eos_id=first
    )
    outs2 = eng2.run([(p, 6), (p, 2)])
    assert len(outs2[0]) == 1 and int(outs2[0][0]) == first


def test_server_per_request_sampling(ctx4):
    """The ``requests`` payload's sampling knobs: scalar broadcast and
    per-request lists reach each Request; a temperature-0 override
    inside a sampled-default engine reproduces the greedy golden."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    p = [5, 9, 2, 4]
    gold = Engine(model, temperature=0.0).serve(
        np.asarray([p], np.int32), gen_len=4
    )[0, 4:]
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, temperature=0.9
    )
    server = ModelServer(eng).start()
    try:
        resp = request(
            server.host, server.port,
            {"requests": [p, p], "gen_lens": [4, 4],
             "temperatures": [0.0, None], "top_ks": 8},
        )
        np.testing.assert_array_equal(
            np.asarray(resp["outputs"][0], np.int32), gold
        )
        assert len(resp["outputs"][1]) == 4
        # Mismatched knob list lengths surface as server errors.
        import pytest

        with pytest.raises(RuntimeError, match="top_ps"):
            request(
                server.host, server.port,
                {"requests": [p], "gen_lens": [2], "top_ps": [0.9, 0.5]},
            )
    finally:
        server.shutdown()


def test_server_speculative_stats(ctx4):
    """A server over a speculative ContinuousEngine serves the same
    tokens and reports the accept/rollback ledger in stats."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    p = [5, 9, 2, 4, 5, 9, 2, 4]
    gold = Engine(model, temperature=0.0).serve(
        np.asarray([p], np.int32), gen_len=6
    )[0, 8:]
    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64, speculative=3
    )
    server = ModelServer(eng).start()
    try:
        resp = request(
            server.host, server.port,
            {"requests": [p], "gen_lens": [6]},
        )
        np.testing.assert_array_equal(
            np.asarray(resp["outputs"][0], np.int32), gold
        )
        assert resp["stats"]["spec_verify_steps"] >= 1
        assert "spec_accept_rate" in resp["stats"]
    finally:
        server.shutdown()


def test_server_unknown_payload_and_malformed_json(ctx4):
    """Unknown payloads return a structured error naming the accepted
    shapes (was: a bare KeyError 'input_ids'); malformed JSON is
    reported AND the connection keeps serving; both bump the server
    error counter exposed via {"cmd": "stats"}."""
    import json
    import socket

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    server = ModelServer(Engine(model, mode="xla")).start()
    try:
        with pytest.raises(RuntimeError, match="accepted payloads"):
            request(server.host, server.port, {"whatever": 1})
        with socket.create_connection(
            (server.host, server.port), timeout=10
        ) as s, s.makefile("rwb") as f:
            f.write(b"{not json}\n")
            f.flush()
            resp = json.loads(f.readline())
            assert resp["error"]["status"] == "bad_request"
            assert "malformed JSON" in resp["error"]["reason"]
            # The SAME connection still serves after the bad line.
            f.write(json.dumps({"cmd": "ping"}).encode() + b"\n")
            f.flush()
            assert json.loads(f.readline())["ok"]
        stats = request(server.host, server.port, {"cmd": "stats"})["stats"]
        assert stats["server"]["errors"] >= 2
    finally:
        server.shutdown()


def test_server_oversized_line_bounded(ctx4):
    """A giant request line is refused at the byte bound (no OOM-sized
    buffering), the connection is dropped (framing is lost), and the
    server stays serviceable."""
    import json
    import socket

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    server = ModelServer(Engine(model, mode="xla")).start()
    server.MAX_LINE_BYTES = 1024  # instance override for the test
    try:
        with socket.create_connection(
            (server.host, server.port), timeout=10
        ) as s, s.makefile("rwb") as f:
            f.write(b"x" * 4096 + b"\n")
            f.flush()
            resp = json.loads(f.readline())
            assert resp["error"]["status"] == "bad_request"
            assert "exceeds" in resp["error"]["reason"]
            assert f.readline() == b""  # server dropped the connection
        # A line far larger than any stream buffer: the server must
        # drain the unread tail before closing, or its close() turns
        # into an RST that destroys the error response client-side.
        with socket.create_connection(
            (server.host, server.port), timeout=10
        ) as s, s.makefile("rwb") as f:
            f.write(b"y" * (1 << 20) + b"\n")
            f.flush()
            resp = json.loads(f.readline())
            assert resp["error"]["status"] == "bad_request"
        assert request(server.host, server.port, {"cmd": "ping"})["ok"]
    finally:
        server.shutdown()


def test_server_client_disconnect_mid_request(ctx4):
    """A client that sends a generation payload and hard-closes (RST)
    before reading must not kill the server: the failure is counted as
    a connection error and the engine/pool stay clean."""
    import json
    import socket
    import struct
    import time as _time

    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = ContinuousEngine(model, max_batch=1, page_size=16, max_length=64)
    server = ModelServer(eng).start()
    try:
        s = socket.create_connection((server.host, server.port), timeout=10)
        s.sendall(json.dumps(
            {"requests": [[5, 9, 2, 4]], "gen_lens": [4]}
        ).encode() + b"\n")
        # SO_LINGER(0): close sends RST, so the server's response write
        # fails instead of landing in a dead buffer.
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     struct.pack("ii", 1, 0))
        s.close()
        deadline = _time.monotonic() + 60
        while _time.monotonic() < deadline:
            stats = request(
                server.host, server.port, {"cmd": "stats"}, timeout=10
            )["stats"]["server"]
            if stats["conn_errors"] >= 1:
                break
            _time.sleep(0.1)
        assert stats["conn_errors"] >= 1
        assert request(server.host, server.port, {"cmd": "ping"})["ok"]
        assert eng.audit() == []
    finally:
        server.shutdown()


def test_server_concurrent_requests_and_stats(ctx4):
    """stats/ping payloads bypass the engine lock: they answer while a
    generation payload is in flight on another connection."""
    import threading

    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = ContinuousEngine(model, max_batch=1, page_size=16, max_length=64)
    server = ModelServer(eng).start()
    try:
        done = {}

        def gen():
            done["resp"] = request(
                server.host, server.port,
                {"requests": [[5, 9, 2, 4]], "gen_lens": [8]},
            )

        t = threading.Thread(target=gen, daemon=True)
        t.start()
        probes = 0
        while t.is_alive():
            r = request(server.host, server.port, {"cmd": "stats"},
                        timeout=10)
            assert "server" in r["stats"]
            assert request(server.host, server.port, {"cmd": "ping"},
                           timeout=10)["ok"]
            probes += 1
        t.join(timeout=60)
        # The probes above answered while (and after) generation ran;
        # at least one stats round trip always completes.
        r = request(server.host, server.port, {"cmd": "stats"}, timeout=10)
        assert r["stats"]["server"]["requests"] >= 1
        assert done["resp"]["results"][0]["status"] == "ok"
    finally:
        server.shutdown()


def test_server_graceful_drain(ctx4):
    """Shutdown while a generation is in flight: the in-flight payload
    finishes and its response arrives intact; a payload on an already-
    open connection is refused with `shutting_down`; fresh connections
    are refused once the listener closes."""
    import json
    import socket
    import threading
    import time as _time

    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = ContinuousEngine(model, max_batch=1, page_size=16, max_length=64)
    server = ModelServer(eng).start()
    done = {}

    def gen():
        done["resp"] = request(
            server.host, server.port,
            {"requests": [[5, 9, 2, 4]], "gen_lens": [12]}, timeout=120,
        )

    t = threading.Thread(target=gen, daemon=True)
    t.start()
    # A second connection, accepted BEFORE the drain begins.
    held = socket.create_connection((server.host, server.port), timeout=10)
    _time.sleep(0.5)  # let the generation payload reach the engine
    assert request(server.host, server.port, {"cmd": "shutdown"})["ok"]
    # New generation work on the held connection is refused...
    with held, held.makefile("rwb") as f:
        f.write(json.dumps(
            {"requests": [[1, 2, 3, 4]], "gen_lens": [2]}
        ).encode() + b"\n")
        f.flush()
        resp = json.loads(f.readline())
        assert resp["error"]["status"] == "shutting_down"
    # ...while the in-flight generation drains to completion.
    t.join(timeout=120)
    assert done["resp"]["results"][0]["status"] == "ok"
    assert len(done["resp"]["outputs"][0]) == 12
    # The listener is (eventually) closed to fresh connections.
    deadline = _time.monotonic() + 10
    refused = False
    while _time.monotonic() < deadline and not refused:
        try:
            socket.create_connection(
                (server.host, server.port), timeout=1
            ).close()
            _time.sleep(0.1)
        except OSError:
            refused = True
    assert refused
    server.shutdown()
    assert eng.audit() == []


def test_server_scrape_while_draining(ctx4):
    """metrics/events/ping verbs keep answering after shutdown has been
    requested but before the in-flight generation finishes (a drain is
    exactly when an operator wants to watch the tier). Post-shutdown a
    connection closes after one response, so each probe rides its own
    pre-opened connection."""
    import json
    import socket
    import threading
    import time as _time

    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = ContinuousEngine(model, max_batch=1, page_size=16, max_length=64)
    server = ModelServer(eng).start()
    done = {}

    def gen():
        done["resp"] = request(
            server.host, server.port,
            {"requests": [[5, 9, 2, 4]], "gen_lens": [16]}, timeout=120,
        )

    def probe(conn, payload):
        with conn, conn.makefile("rwb") as f:
            f.write(json.dumps(payload).encode() + b"\n")
            f.flush()
            return json.loads(f.readline())

    t = threading.Thread(target=gen, daemon=True)
    t.start()
    # Pre-open the probe connections BEFORE the drain begins (the
    # listener closes to fresh connections shortly after shutdown).
    conns = [
        socket.create_connection((server.host, server.port), timeout=10)
        for _ in range(3)
    ]
    _time.sleep(0.3)  # let the generation payload reach the engine
    assert request(server.host, server.port, {"cmd": "shutdown"})["ok"]

    ping = probe(conns[0], {"cmd": "ping"})
    assert ping["ok"] and ping["draining"]
    m = probe(conns[1], {"cmd": "metrics"})
    assert "prometheus" in m and "tdt_" in m["prometheus"]
    ev = probe(conns[2], {"cmd": "events"})
    assert "events" in ev and "next_since" in ev

    # The drained generation still finishes intact.
    t.join(timeout=120)
    assert done["resp"]["results"][0]["status"] == "ok"
    assert len(done["resp"]["outputs"][0]) == 16
    server.shutdown()
    assert eng.audit() == []


def test_client_honors_server_backoff_hint(ctx4):
    """The overloaded shed reply carries ``retry_after_s``; the client
    retry loop sleeps THAT instead of its local exponential backoff —
    a local backoff_s large enough to fail the test proves the hint
    was used."""
    import json
    import socket
    import threading
    import time as _time

    hint = 0.05
    seen = []
    lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(4)
    host, port = lsock.getsockname()

    def fake_server():
        # First payload: overloaded + hint; second: success.
        for i in range(2):
            conn, _ = lsock.accept()
            with conn, conn.makefile("rwb") as f:
                f.readline()
                seen.append(_time.monotonic())
                resp = (
                    {"error": {"status": "overloaded", "reason": "full",
                               "retry_after_s": hint}}
                    if i == 0 else {"ok": True}
                )
                f.write(json.dumps(resp).encode() + b"\n")
                f.flush()

    t = threading.Thread(target=fake_server, daemon=True)
    t.start()
    try:
        t0 = _time.monotonic()
        resp = request(host, port, {"cmd": "ping"}, timeout=10,
                       retries=2, backoff_s=30.0)
        wall = _time.monotonic() - t0
        assert resp["ok"]
        assert len(seen) == 2
        # Retried after ~hint seconds, nowhere near the 30 s local
        # backoff; >= proves it actually slept the hint.
        assert hint <= (seen[1] - seen[0]) < 5.0
        assert wall < 10.0
    finally:
        lsock.close()
        t.join(timeout=10)

    # A real server's shed reply carries the hint on the wire.
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    eng = ContinuousEngine(model, max_batch=1, page_size=16, max_length=64)
    server = ModelServer(eng, max_pending=0).start()
    try:
        with pytest.raises(RuntimeError, match="server error") as ei:
            request(server.host, server.port,
                    {"requests": [[1, 2, 3, 4]], "gen_lens": [2]})
        assert "retry_after_s" in str(ei.value)
    finally:
        server.shutdown()


def test_engine_serve_profile_hook(ctx4, tmp_path):
    """Engine.serve(profile=...) must capture a decode-loop trace
    (parity: the reference Engine's built-in profiled decode,
    ``models/engine.py:151-177``) — files on disk, output unchanged."""
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    prompt = np.arange(8, dtype=np.int32)[None]
    eng = Engine(model, temperature=0.0, mode="xla")
    gold = eng.serve(prompt, gen_len=4)
    prof_dir = str(tmp_path / "decode_trace")
    out = eng.serve(prompt, gen_len=4, profile=prof_dir)
    np.testing.assert_array_equal(out, gold)
    import os as _os

    captured = [
        _os.path.join(r, f)
        for r, _d, fs in _os.walk(prof_dir) for f in fs
    ]
    assert captured, f"no trace files under {prof_dir}"
