"""Model-server tests: protocol round trip vs direct Engine output.

Parity model: the reference's server is exercised by its chat/bench
clients (``mega_triton_kernel/test/models/``); here the client is
in-process and the golden is ``Engine.serve`` on the same weights.
"""

import numpy as np

from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.serving import ModelServer, request


def test_server_round_trip(ctx4):
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    engine = Engine(model, temperature=0.0, mode="xla")

    prompts = np.asarray([[1, 2, 3, 4, 5, 6, 7, 8]], np.int32)
    gold = engine.serve(prompts, gen_len=4)

    server = ModelServer(engine).start()
    try:
        assert request(server.host, server.port, {"cmd": "ping"})["ok"]
        resp = request(
            server.host, server.port,
            {"input_ids": prompts.tolist(), "gen_len": 4},
        )
        np.testing.assert_array_equal(
            np.asarray(resp["output_ids"], np.int32), gold
        )
        assert "decode_ms_per_step" in resp["stats"]
    finally:
        server.shutdown()


def test_server_reports_errors(ctx4):
    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    engine = Engine(model, mode="xla")
    server = ModelServer(engine).start()
    try:
        import pytest

        # Indivisible prompt lengths are auto-padded now — serve works.
        resp = request(
            server.host, server.port,
            {"input_ids": [[1, 2, 3]], "gen_len": 2},  # len 3 % tp4 != 0
        )
        assert np.asarray(resp["output_ids"]).shape == (1, 5)

        # A malformed request still surfaces as a server error.
        with pytest.raises(RuntimeError, match="server error"):
            request(
                server.host, server.port,
                {"input_ids": [[1, 2, 3]], "gen_len": 2,
                 "prompt_start": [7]},  # out of range for s=3
            )
    finally:
        server.shutdown()
