"""Process-fleet supervision tests (docs/scale-out.md "Process
fleet"): wire-protocol replicas, heartbeats, crash respawn, and
bit-exact in-flight recovery.

Layers of evidence:

- pure ticket-latch races and retry-backoff math — milliseconds, no
  processes;
- a single stub-replica child behind ``RemoteReplica``: wire round
  trip bit-exact vs the stub's pure generator, affinity digest over
  the wire, remote audit, structured no-survivor failure on a dropped
  wire;
- the chaos layer (ISSUE-9 acceptance): a replica process SIGKILLed
  MID-BATCH through the seeded ``proc.kill`` seam has every in-flight
  ticket re-routed and finished bit-exact, survivors audit clean, and
  the supervisor respawns the slot — which then serves a routed
  request under a fresh prefix digest. SIGSTOP drives both the
  heartbeat-wedge classification and the true multi-process latch
  race (two completions for one ticket id; the late one discards).

Every process test spawns ``run_server --model stub`` children
(models/stub.py: real radix control plane, hash "model", no model
load) and synchronizes on conditions with deadlines — never on bare
sleeps. The whole file skips where child processes cannot be spawned.
"""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from triton_distributed_tpu.models.continuous import RequestResult
from triton_distributed_tpu.models.stub import StubEngine, stub_generate
from triton_distributed_tpu.runtime.faults import FaultPlan
from triton_distributed_tpu.serving.replica import Ticket


def _can_spawn() -> bool:
    try:
        return subprocess.run(
            [sys.executable, "-c", "pass"], timeout=60
        ).returncode == 0
    except Exception:  # noqa: BLE001 — any failure means "cannot"
        return False


_SPAWN_OK = _can_spawn()
needs_procs = pytest.mark.skipif(
    not _SPAWN_OK or not hasattr(signal, "SIGKILL"),
    reason="child-process spawning unavailable on this platform",
)

PROMPTS = [
    np.arange(1, 9, dtype=np.int32),
    np.arange(20, 30, dtype=np.int32),
    np.arange(40, 46, dtype=np.int32),
]
GENS = [5, 4, 3]
GOLDS = [stub_generate(p, g) for p, g in zip(PROMPTS, GENS)]


def _stub_specs(n, delay_s=0.4):
    from triton_distributed_tpu.serving.supervisor import stub_spec

    return [
        stub_spec(f"r{i}", delay_s=delay_s, page_size=4, num_pages=64)
        for i in range(n)
    ]


def _spawn_fleet(n, delay_s=0.4, spawn_timeout_s=120.0):
    """N unmanaged RemoteReplicas (no supervisor), spawned in
    parallel; returns the replica list."""
    from triton_distributed_tpu.serving.supervisor import spawn_replica

    out = {}

    def boot(i, spec):
        out[i] = spawn_replica(spec, spawn_timeout_s=spawn_timeout_s)

    threads = [
        threading.Thread(target=boot, args=(i, s), daemon=True)
        for i, s in enumerate(_stub_specs(n, delay_s))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(out) == n, f"only {len(out)}/{n} replicas spawned"
    return [out[i] for i in range(n)]


def _reap(replicas):
    for r in replicas:
        proc = getattr(r, "proc", None)
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


# -- pure: ticket latch races and backoff math ---------------------------


def test_ticket_latch_first_and_claim_races():
    """The at-least-once contract in miniature: exactly one completion
    latches per ticket id, and the per-hop reroute claim can neither
    double-dispatch nor strand a ticket."""
    t = Ticket(PROMPTS[0], 4)
    assert t.tid and t.tid != Ticket(PROMPTS[0], 4).tid  # unique ids
    r1 = RequestResult(np.asarray([1, 2], np.int32))
    r2 = RequestResult(np.asarray([9, 9], np.int32), "failed", "late")
    assert t.complete(r1) is True
    # Second completion for the SAME ticket id (the dead replica
    # actually finished): discarded, first result untouched.
    assert t.complete(r2) is False
    assert t.result is r1
    # A latched ticket can never be claimed for re-dispatch.
    assert t.claim_reroute("r0") is False

    # Per-hop claim: the death callback and the timeout path race to
    # re-route the same hop; exactly one wins.
    t2 = Ticket(PROMPTS[0], 4)
    t2.replica_history.append("r0")
    assert t2.claim_reroute("r0") is True
    assert t2.claim_reroute("r0") is False  # same hop, second claimant
    assert t2.reroutes == 1
    # Re-dispatched to r1: a LATE claim against the old hop loses...
    t2.replica_history.append("r1")
    assert t2.claim_reroute("r0") is False
    # ...but r1's own failure can still claim its hop (no strand).
    assert t2.claim_reroute("r1") is True
    assert t2.reroutes == 2


def test_retry_backoff_cap_and_jitter():
    """ISSUE-9 satellite: the client retry delay is capped at
    ``max_backoff_s`` and jittered ±20%, so a respawning fleet never
    sees a synchronized retry storm."""
    from triton_distributed_tpu.serving.server import _retry_backoff

    for attempt in range(12):
        d = _retry_backoff(attempt, 0.25, 1.0)
        base = min(0.25 * (2 ** attempt), 1.0)
        assert 0.8 * base <= d <= 1.2 * base
        assert d <= 1.2  # the cap holds however far attempts run
    # Deep attempts land in the capped jitter band, not at one point.
    deep = {round(_retry_backoff(20, 0.25, 1.0), 6) for _ in range(32)}
    assert all(0.8 <= d <= 1.2 for d in deep)
    assert len(deep) > 1  # jitter actually jitters


def test_request_retries_against_fake_shedding_server(monkeypatch):
    """The cap through the real retry loop: a fake server that always
    sheds (no retry_after_s hint) drives ``request(retries=3)``
    through capped, jittered local backoff; the recorded sleeps never
    exceed 1.2 × max_backoff_s."""
    import json
    import socket as socket_mod

    from triton_distributed_tpu.serving import server as server_mod

    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    host, port = srv.getsockname()
    stop = threading.Event()

    def shed_forever():
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket_mod.timeout:
                continue
            with conn, conn.makefile("rwb") as f:
                if f.readline():
                    f.write(json.dumps(
                        {"error": {"status": "overloaded",
                                   "reason": "always shedding"}}
                    ).encode() + b"\n")
                    f.flush()

    th = threading.Thread(target=shed_forever, daemon=True)
    th.start()
    slept = []

    class _TimeShim:
        """server_mod-local stand-in: recording sleep, real clocks —
        patching the module ATTRIBUTE keeps the global time module
        untouched for every other thread."""

        sleep = staticmethod(lambda s: slept.append(s))
        monotonic = staticmethod(time.monotonic)

    monkeypatch.setattr(server_mod, "time", _TimeShim)
    try:
        with pytest.raises(RuntimeError, match="overloaded"):
            server_mod.request(
                host, port, {"cmd": "nope"}, timeout=10,
                retries=3, backoff_s=0.5, max_backoff_s=0.6,
            )
    finally:
        stop.set()
        th.join(timeout=5)
        srv.close()
    assert len(slept) == 3  # one backoff per retry
    assert all(s <= 0.6 * 1.2 + 1e-9 for s in slept)
    # attempts 1+ would be 1.0/2.0 uncapped — the cap actually bit.
    assert all(s >= 0.4 * 0.8 for s in slept)


def test_wire_fault_menu_units():
    """The new FaultPlan conveniences arm the seams they claim."""
    from triton_distributed_tpu.runtime.faults import mutate_point

    with FaultPlan(seed=1).garble_wire("recv", replica="rX"):
        # Probe traffic is NOT matched by default — a supervisor
        # heartbeat must never race a batch-targeted rule for the hit.
        probe = mutate_point("wire.recv", b'{"ok": true}\n',
                             replica="rX", what="probe")
        assert probe == b'{"ok": true}\n'
        out = mutate_point("wire.recv", b'{"ok": true}\n',
                           replica="rX", what="batch")
        assert out == bytes(reversed(b'{"ok": true}\n'))
    with FaultPlan(seed=1).drop_wire("send", replica="rX"):
        with pytest.raises(ConnectionResetError):
            mutate_point("wire.send", b"payload", replica="rX",
                         what="batch")
    # A kill rule against a replica with no pid yet is a no-op.
    with FaultPlan(seed=1).kill_proc(replica="rX"):
        assert mutate_point("proc.kill", None, replica="rX") is None
    with pytest.raises(ValueError, match="side"):
        FaultPlan().drop_wire("sideways")


# -- one child: wire round trip, affinity, audit, no-survivor -----------


@needs_procs
def test_remote_replica_roundtrip_and_no_survivor(fresh_telemetry):
    """One stub child behind RemoteReplica + Router: outputs bit-exact
    vs the pure generator, the digest piggyback feeds affinity, the
    audit verb answers over the wire, the front ModelServer composes —
    and a dropped wire with no survivors fails structured, never
    hangs."""
    from triton_distributed_tpu.serving import ModelServer, request
    from triton_distributed_tpu.serving.router import Router

    reps = _spawn_fleet(1, delay_s=0.0)
    router = Router(reps)
    try:
        res = router.run(list(zip(PROMPTS, GENS)), results=True)
        for r, gold in zip(res, GOLDS):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == gold
        # Digest piggyback: the replica's published mirror now scores
        # the same prompt as cached (affinity over the wire).
        assert reps[0].match_len(PROMPTS[0]) > 0
        res = router.run([(PROMPTS[0], GENS[0])], results=True)
        assert res[0].tokens.tolist() == GOLDS[0]
        assert router.last_stats["router"]["affinity_hits"] >= 1
        # Fleet totals aggregate the child's stats over the wire.
        assert router.last_stats["generated_tokens"] == sum(GENS) + GENS[0]
        # Remote audit: the child's pool/radix invariants, via the verb.
        assert router.audit() == []
        # healthz: cheap liveness with drain-vs-death state.
        assert reps[0].healthz() == {"ok": True, "state": "serving"}

        # Front server over the remote fleet: the full double-wire path.
        front = ModelServer(router).start()
        try:
            resp = request(
                front.host, front.port,
                {"requests": [PROMPTS[1].tolist()], "gen_lens": [GENS[1]]},
            )
            assert resp["outputs"][0] == GOLDS[1]
            assert resp["stats"]["router"]["routed"] >= 5
        finally:
            front._shutdown.set()

        # Wire drop with NO survivors: structured failure, no hang.
        with FaultPlan(seed=5).drop_wire(
            "recv", replica="r0", times=99
        ) as plan:
            res = router.run([(PROMPTS[2], 2)], results=True)
        assert plan.fired
        assert res[0].status == "failed"
        assert "routing failed" in res[0].reason
        assert reps[0].state == "dead"
        assert "wire failure" in reps[0].last_error
    finally:
        router.shutdown()
        _reap(reps)


# -- chaos: SIGKILL mid-batch, respawn, rejoin ---------------------------


@needs_procs
def test_fleet_sigkill_mid_batch_recovers_and_respawns(fresh_telemetry):
    """ISSUE-9 acceptance: a replica process SIGKILLed mid-batch (the
    seeded ``proc.kill`` seam fires the instant its batch is on the
    wire) yields bit-exact survivor outputs and clean survivor audits;
    the supervisor classifies the crash, respawns the slot with a
    fresh name and digest, and the respawned replica serves a routed
    request."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    sup = FleetSupervisor(
        _stub_specs(2, delay_s=0.4),
        heartbeat_s=0.1, heartbeat_timeout_s=2.0,
        respawn_backoff_s=0.2, spawn_timeout_s=120.0,
    )
    try:
        router = sup.start()
        plan = FaultPlan(seed=7).kill_proc(replica="r0")
        with plan:
            res = router.run(list(zip(PROMPTS, GENS)), results=True)
        assert plan.fired and plan.fired[0][0] == "proc.kill"
        # 100% of in-flight requests recovered, bit-exact (the
        # ticket-id dedup makes the at-least-once overlap safe).
        for r, gold in zip(res, GOLDS):
            assert r.status == "ok", (r.status, r.reason)
            assert r.tokens.tolist() == gold
        st = router.last_stats["router"]
        assert st["reroutes"] >= 1
        assert router.replica("r1").state == "healthy"
        # Survivors audit clean over the wire.
        assert router.audit() == []

        # The supervisor respawns the slot; the new replica joins
        # under a fresh generation name with a FRESH (empty) digest.
        assert sup.wait_healthy(2, timeout_s=60)
        names = [r.name for r in router.replicas]
        assert "r0#1" in names and "r1" in names
        reborn = router.replica("r0#1")
        assert reborn.match_len(PROMPTS[0]) == 0  # fresh digest
        assert router.last_stats["router"]["retired_replicas"] == 1

        # The respawned replica serves a routed request: drain the
        # survivor so routing MUST land on the newcomer.
        assert router.drain_replica("r1", grace_s=30)
        res = router.run([(PROMPTS[0], GENS[0])], results=True)
        assert res[0].status == "ok"
        assert res[0].tokens.tolist() == GOLDS[0]
        assert reborn.served >= 1

        kinds = [e.kind for e in obs_events.default_ring().tail(0)[0]]
        for k in ("fault", "replica_dead", "reroute",
                  "replica_proc_failed", "replica_respawn"):
            assert k in kinds, f"missing {k} in {set(kinds)}"
        ledger = sup.stats()["slots"][0]
        assert ledger["generation"] == 1 and ledger["respawns"] == 1
        from triton_distributed_tpu.obs import metrics as obs_metrics

        snap = obs_metrics.default_registry().snapshot()
        fails = snap["tdt_supervisor_failures_total"]["series"]
        assert any(
            s["labels"]["replica"] == "r0" and s["value"] >= 1
            for s in fails
        )
        spawns = snap["tdt_supervisor_respawns_total"]["series"]
        assert [s["value"] for s in spawns
                if s["labels"]["replica"] == "r0"] == [1]
    finally:
        sup.shutdown()


@needs_procs
def test_fleet_hang_latch_race_two_completions(fresh_telemetry):
    """ISSUE-9 satellite: the true multi-process latch race. A child
    SIGSTOPped mid-batch trips the router's request timeout; the
    ticket re-routes and completes on the survivor. SIGCONT then lets
    the wedged child finish and push a SECOND completion for the same
    ticket id up the still-open connection — it latch-loses, the
    result is unchanged, and the duplicate batch never enters fleet
    accounting."""
    from triton_distributed_tpu.serving.router import Router

    reps = _spawn_fleet(2, delay_s=0.3)
    r0, r1 = reps
    router = Router(reps, request_timeout_s=1.5)
    try:
        plan = FaultPlan(seed=3).hang_proc(replica="r0")
        with plan:
            res = router.run([(PROMPTS[0], GENS[0])], results=True)
            assert plan.fired
            assert res[0].status == "ok"
            assert res[0].tokens.tolist() == GOLDS[0]
            assert r0.state == "dead" and "timeout" in r0.last_error
            assert router.stats["reroutes"] >= 1
            first = res[0]
            # Wake the wedged child: its late response arrives on the
            # worker's still-open socket and must be discarded by id.
            os.kill(r0.pid, signal.SIGCONT)
            r0.join(timeout=60)
        assert res[0] is first  # the latch never moved
        assert res[0].tokens.tolist() == GOLDS[0]
        # The duplicate batch stayed out of the dead replica's ledger.
        assert r0.served == 0 and r0.runs == 0
        assert r0.totals["generated_tokens"] == 0
        assert router.audit() == []  # survivor clean; dead skipped
    finally:
        router.shutdown()
        _reap(reps)


@needs_procs
def test_supervisor_heartbeat_wedge_classified(fresh_telemetry):
    """A wedged-but-alive process (SIGSTOP, no batch in flight) is
    detectable ONLY by the heartbeat deadline: the supervisor
    classifies ``heartbeat_timeout``, SIGKILLs the zombie, and
    respawns the slot."""
    from triton_distributed_tpu.serving.supervisor import FleetSupervisor

    sup = FleetSupervisor(
        _stub_specs(2, delay_s=0.0),
        heartbeat_s=0.1, heartbeat_timeout_s=1.0, heartbeat_misses=2,
        respawn_backoff_s=0.2, spawn_timeout_s=120.0,
    )
    try:
        router = sup.start()
        # Let the first beats land so the wedge is a state CHANGE.
        assert sup.wait_for(
            lambda: sup.slot("r0").last_beat_t is not None, 30
        )
        os.kill(router.replica("r0").pid, signal.SIGSTOP)
        assert sup.wait_for(
            lambda: (sup.slot("r0").last_failure or "").startswith(
                "heartbeat_timeout"
            ),
            timeout_s=30,
        ), sup.stats()
        # The zombie was killed and the slot respawned.
        assert sup.wait_healthy(2, timeout_s=60)
        res = router.run([(PROMPTS[0], 2)], results=True)
        assert res[0].status == "ok"
        assert res[0].tokens.tolist() == stub_generate(PROMPTS[0], 2)
    finally:
        sup.shutdown()


@needs_procs
def test_crash_loop_circuit_breaker_parks(fresh_telemetry):
    """A slot that can never come up (its child exits before binding)
    burns its crash budget and is PARKED — event + counter fire and
    the fleet keeps serving degraded on the survivor instead of
    spinning on doomed spawns."""
    from triton_distributed_tpu.obs import events as obs_events
    from triton_distributed_tpu.serving.supervisor import (
        FleetSupervisor,
        ReplicaSpec,
    )

    bad = ReplicaSpec("bad", [sys.executable, "-c", "pass"])
    sup = FleetSupervisor(
        _stub_specs(1, delay_s=0.0) + [bad],
        heartbeat_s=0.05, spawn_timeout_s=15.0,
        respawn_backoff_s=0.1, max_backoff_s=0.2,
        crash_limit=2, crash_window_s=60.0,
    )
    try:
        router = sup.start()
        assert [r.name for r in router.replicas] == ["r0"]
        assert sup.wait_for(lambda: sup.slot("bad").parked, 60), \
            sup.stats()
        assert sup.slot("bad").last_failure.startswith("spawn")
        # Degraded but serving.
        res = router.run([(PROMPTS[0], 2)], results=True)
        assert res[0].status == "ok"
        kinds = [e.kind for e in obs_events.default_ring().tail(0)[0]]
        assert "replica_parked" in kinds
        from triton_distributed_tpu.obs import metrics as obs_metrics

        snap = obs_metrics.default_registry().snapshot()
        parked = snap["tdt_supervisor_parked_replicas"]["series"]
        assert parked == [{"labels": {}, "value": 1}]
    finally:
        sup.shutdown()
