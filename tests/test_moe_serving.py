"""MoE serving tests (ISSUE-11 acceptance core): Qwen3MoE through the
paged/continuous stack + the megakernel's split-phase EP combine.

Layers of evidence:

- **engine level**: Qwen3MoE through ``ContinuousEngine`` — bf16(f32)
  + int8 pools × greedy + seeded sampling, bit-exact vs single-request
  goldens, prefix-cache reuse/COW/eviction with clean pool/radix audits
  (the conftest autouse fixture re-audits every live engine after every
  test), speculation riding the inherited chunk-verify path;
- **megakernel level**: ``mode="mega"`` serves the MoE model via the
  EP-resharded expert streams + MOE_GATE/MOE_FFN/A2A tasks — greedy
  parity vs the unfused engine at tp=1 (tp=4 rides the slow marker,
  like the other interpret-heavy multi-rank suites), the device trace
  ring validating every A2A_SEND/A2A_WAIT scoreboard edge
  (``obs.kernel_trace.validate_ring`` over the scheduled order), and
  the measured A2A overlap report;
- **satellites**: ``SlotSnapshot`` round-trips an MoE slot (the
  geometry is model-agnostic — guarded here), ``server_stats.engine``
  reports the expert knobs, and ``last_stats`` carries the
  ``moe_routed_tokens``/``a2a_dropped`` ledger.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.models.continuous import (
    ContinuousEngine,
    Request,
)
from triton_distributed_tpu.runtime import mesh as mesh_mod


@pytest.fixture(scope="module")
def moe_model():
    """ONE tiny-moe model on a single device for the whole module (the
    test_router/test_migration rationale: model init and the first
    compiled programs dominate; every test shares them)."""
    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    model = AutoLLM.from_pretrained("tiny-moe", ctx=ctx)
    yield model
    mesh_mod.finalize_distributed()


PROMPTS = [
    np.arange(1, 13, dtype=np.int32),
    np.arange(30, 40, dtype=np.int32),
    np.arange(1, 13, dtype=np.int32),  # exact repeat → radix hit
]
GENS = [8, 6, 8]


def make_engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("page_size", 16)
    kw.setdefault("max_length", 64)
    kw.setdefault("prefix_cache", True)
    return ContinuousEngine(model, **kw)


def goldens(model, reqs, **kw):
    """Single-request, single-slot runs — the bit-exactness reference
    (each request decodes alone, so batching effects can't hide)."""
    outs = []
    for r in reqs:
        eng = make_engine(model, max_batch=1, **kw)
        outs.append(eng.run([r], results=True)[0].tokens.tolist())
        assert eng.audit() == []
    return outs


# -- engine level ---------------------------------------------------------


@pytest.mark.parametrize("kv_dtype", [None, "int8"])
def test_moe_continuous_greedy_bit_exact(moe_model, kv_dtype):
    """Batched continuous serving of the MoE model is bit-exact vs the
    single-request goldens on both pool dtypes, audits clean."""
    reqs = list(zip(PROMPTS, GENS))
    gold = goldens(moe_model, reqs, kv_dtype=kv_dtype)
    eng = make_engine(moe_model, kv_dtype=kv_dtype)
    res = eng.run(reqs, results=True)
    assert all(r.ok for r in res)
    assert [r.tokens.tolist() for r in res] == gold
    assert eng.audit() == []
    st = eng.last_stats
    # The MoE ledger: routed assignments = processed positions × top_k,
    # and the lossless path's drop counter is 0 by construction.
    assert st["num_experts"] == moe_model.cfg.num_experts
    assert st["experts_per_tok"] == moe_model.cfg.num_experts_per_tok
    assert st["moe_routed_tokens"] > 0
    assert st["a2a_dropped"] == 0
    # Work accounting ties out: every prefilled position routed top_k
    # assignments, plus top_k per active slot per decode step.
    assert st["moe_routed_tokens"] % moe_model.cfg.num_experts_per_tok == 0


def test_moe_continuous_seeded_sampling_bit_exact(moe_model):
    """Seeded per-request sampling through the MoE model: with
    explicit per-request keys, a batched run is bit-identical to the
    single-request goldens (every draw is fold_in(request key, draw
    counter) — the per-request PRNG protocol, guarded on MoE here)."""

    def reqs():
        return [
            Request(p, g, temperature=0.8, top_p=0.9,
                    key=jax.random.key(100 + i))
            for i, (p, g) in enumerate(zip(PROMPTS, GENS))
        ]

    gold = goldens(moe_model, reqs(), kv_dtype="int8", seed=11)
    eng = make_engine(moe_model, kv_dtype="int8", seed=11)
    res = eng.run(reqs(), results=True)
    assert [r.tokens.tolist() for r in res] == gold
    assert eng.audit() == []


def test_moe_prefix_cache_reuse_cow_eviction(moe_model):
    """Radix reuse on the MoE model: the repeated prompt admits with
    prefix hits, a diverging tail COW-clones, and eviction pressure
    leaves the audits clean."""
    eng = make_engine(moe_model, kv_dtype="int8", num_pages=12)
    base = np.arange(1, 17, dtype=np.int32)
    eng.run([(base, 6)])
    st1 = dict(eng.last_stats)
    # Same prompt again: the tree serves the prefix.
    eng.run([(base, 6)])
    st2 = eng.last_stats
    assert st2["prefix_hit_tokens"] > 0
    assert st2["prefill_tokens"] < st1["prefill_tokens"]
    # Diverging tail on a shared page boundary → COW clone.
    fork = base.copy()
    fork[-1] += 1
    eng.run([(fork, 6)])
    assert eng.last_stats["pages_cow_copied"] >= 1
    # Eviction pressure: a stream of disjoint prompts through a small
    # pool forces LRU eviction; audits stay clean throughout (the
    # autouse fixture re-checks after the test too).
    for lo in range(50, 110, 12):
        eng.run([(np.arange(lo, lo + 12, dtype=np.int32), 4)])
        assert eng.audit() == []


def test_moe_speculative_greedy_parity(moe_model):
    """Self-drafting speculation rides the inherited chunk-verify path
    for MoE: greedy output matches the non-speculative run and the
    accept ledger moves."""
    # Period-3 repetition gives the n-gram drafter material.
    p = np.asarray([5, 6, 7] * 5, np.int32)
    base = make_engine(moe_model)
    gold = base.run([(p, 8)], results=True)[0].tokens.tolist()
    eng = make_engine(moe_model, speculative=2)
    res = eng.run([(p, 8)], results=True)
    assert res[0].tokens.tolist() == gold
    assert eng.last_stats["spec_verify_steps"] > 0
    assert eng.audit() == []


# -- megakernel level -----------------------------------------------------


def test_moe_mega_greedy_parity_tp1(moe_model, fresh_telemetry):
    """mode='mega' (EP expert streams + split-phase A2A combine under
    the serving default config) matches the unfused engine
    token-for-token, with the device tracer live: launches carry A2A
    windows and the measured overlap report is populated."""
    reqs = list(zip(PROMPTS, GENS))
    gold_eng = make_engine(moe_model)
    gold = [r.tokens.tolist()
            for r in gold_eng.run(reqs, results=True)]
    eng = make_engine(moe_model, mode="mega", kernel_trace=True)
    res = eng.run(reqs, results=True)
    assert [r.tokens.tolist() for r in res] == gold
    assert eng.audit() == []
    st = eng.last_stats
    assert st["mega_launches"] > 0
    assert st["moe_routed_tokens"] > 0
    summ = eng.kernel_trace_summary()
    assert summ["launches"] == st["mega_trace_launches"]
    rep = summ["recent"][-1]["overlap"]
    assert rep["a2a_windows"] > 0
    assert rep["a2a_hidden_fraction"] is not None


def test_moe_mega_a2a_ring_validation_tp1(moe_model):
    """Every A2A_SEND/A2A_WAIT scoreboard edge of a traced multi-step
    MoE launch holds on the device clock (``validate_ring`` over the
    scheduled order), and the graph carries the expected MoE tasks."""
    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig
    from triton_distributed_tpu.megakernel.task import TaskType
    from triton_distributed_tpu.obs import kernel_trace as kt

    model = moe_model
    cache = model.new_cache(2, 64)
    toks = jnp.asarray(np.arange(16, dtype=np.int32).reshape(2, 8))
    lg, cache = model.prefill_batched(toks, cache, "xla")
    tok = jnp.argmax(lg, -1).astype(jnp.int32)
    mega = MegaQwen3(model, cfg=MegaConfig(
        fuse_norms=True, cross_prefetch=True, overlap_ar=True,
    ))
    NS = 3
    fn = mega.decode_multi_fn(2, 64, NS, trace=True)
    order = mega.multi_task_order(2, 64, NS, trace=True)
    ops = {t.task_type for t in order}
    assert {TaskType.MOE_GATE, TaskType.MOE_FFN,
            TaskType.A2A_SEND, TaskType.A2A_WAIT} <= ops
    assert TaskType.FC1 not in ops and TaskType.FC2 not in ops
    # Per layer: one gate, E/n expert tasks, two phase sends, one wait.
    epr = model.cfg.num_experts  # tp=1 → all experts local
    sends = [t for t in order if t.task_type == TaskType.A2A_SEND]
    assert len(sends) == 2 * model.cfg.num_layers
    assert sorted({t.arg0 for t in sends}) == [0, 1]
    assert sum(
        1 for t in order if t.task_type == TaskType.MOE_FFN
    ) == epr * model.cfg.num_layers
    _toks, _logits, _cache, ring = fn(mega._step_params(), tok, cache)
    records = kt.decode_trace(np.asarray(ring))
    assert kt.validate_ring(records, order) == []
    rep = kt.overlap_report(records)
    assert rep["a2a_windows"] == model.cfg.num_layers * NS
    assert rep["a2a_comm_ticks"] > 0
    assert rep["a2a_hidden_ticks"] > 0


@pytest.mark.slow
def test_moe_mega_ring_validated_tp4():
    """tp=4: EP-sharded experts (2 local experts/rank), greedy parity
    vs the unfused chain, and ring validation of every scoreboard edge
    — including the A2A pair's — on all four ranks."""
    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig
    from triton_distributed_tpu.obs import kernel_trace as kt

    ctx = mesh_mod.initialize_distributed(tp=4, devices=jax.devices()[:4])
    try:
        model = AutoLLM.from_pretrained("tiny-moe", ctx=ctx)
        cache = model.new_cache(2, 64)
        toks = jnp.asarray(np.arange(16, dtype=np.int32).reshape(2, 8))
        lg, cache = model.prefill_batched(toks, cache, "xla")
        tok = jnp.argmax(lg, -1).astype(jnp.int32)
        mega = MegaQwen3(model, cfg=MegaConfig(
            fuse_norms=True, cross_prefetch=True, overlap_ar=True,
        ))
        NS = 3
        fn = mega.decode_multi_fn(2, 64, NS, trace=True)
        order = mega.multi_task_order(2, 64, NS, trace=True)
        mtoks, _lg, _c, ring = fn(
            mega._step_params(), tok, jax.tree.map(jnp.copy, cache)
        )
        # Unfused greedy chain over the same cache.
        t = tok
        chain = []
        for _ in range(NS):
            lx, cache = model.decode_step(t, cache, "xla")
            t = jnp.argmax(lx, -1).astype(jnp.int32)
            chain.append(np.asarray(t))
        assert np.array_equal(np.asarray(mtoks), np.stack(chain))
        records = kt.decode_trace(np.asarray(ring))
        assert kt.validate_ring(records, order) == []
        rep = kt.overlap_report(records)
        assert rep["a2a_windows"] == model.cfg.num_layers * NS * 4
        assert rep["a2a_hidden_fraction"] > 0
    finally:
        mesh_mod.finalize_distributed()


@pytest.mark.slow
def test_moe_mega_int8_single_step_parity(moe_model):
    """Single-step mega decode over an int8 MoE pool: greedy tokens
    match the unfused int8 path step-for-step (the NS-launch band
    carries the PR 7 band-precision tolerance instead — its rows are
    full precision while the unfused path re-reads them quantized)."""
    from triton_distributed_tpu.megakernel import MegaQwen3
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig
    from triton_distributed_tpu.models.paged_kv_cache import (
        init_paged_cache,
        write_prefill,
    )

    model = moe_model
    paged, _pool = init_paged_cache(
        model.cfg, 2, model.ctx, max_length=64, page_size=16,
        kv_dtype="int8",
    )
    dense1 = model.new_cache(1, 64)
    toks = np.arange(16, dtype=np.int32).reshape(2, 8)
    last = []
    for i in range(2):
        li, dense1 = model.prefill_batched(
            jnp.asarray(toks[i:i + 1]), dense1, "xla"
        )
        paged = write_prefill(paged, i, dense1.k, dense1.v, 8)
        last.append(li[0])
    tok = jnp.argmax(jnp.stack(last), -1).astype(jnp.int32)
    mega = MegaQwen3(model, cfg=MegaConfig(
        fuse_norms=True, cross_prefetch=True, overlap_ar=True,
    ))
    cu = jax.tree.map(jnp.copy, paged)
    cm = jax.tree.map(jnp.copy, paged)
    tu = tm = tok
    for _ in range(5):
        lu, cu = model.decode_step(tu, cu, "xla")
        lm, cm = mega.decode_step(tm, cm)
        tu = jnp.argmax(lu, -1).astype(jnp.int32)
        tm = jnp.argmax(lm, -1).astype(jnp.int32)
        assert tu.tolist() == tm.tolist()


# -- satellites -----------------------------------------------------------


def test_moe_slot_snapshot_roundtrip(moe_model):
    """``migrate.export`` smoke (ISSUE-11 satellite): a mid-generation
    MoE slot exports, round-trips the wire codec, and imports into a
    SECOND engine whose remaining tokens are bit-identical — the
    snapshot geometry is model-agnostic and stays that way."""
    from triton_distributed_tpu.models import slot_state

    reqs = list(zip(PROMPTS[:2], GENS[:2]))
    gold = [
        r.tokens.tolist()
        for r in make_engine(moe_model, kv_dtype="int8").run(
            reqs, results=True
        )
    ]
    A = make_engine(moe_model, kv_dtype="int8")
    A.request_handoff(after_rounds=2)
    res1 = A.run(reqs, results=True)
    assert all(r.status == "migrated" for r in res1)
    assert A.audit() == []
    B = make_engine(moe_model, kv_dtype="int8")
    resume = []
    for (p, g), r in zip(reqs, res1):
        # Wire round trip before resuming (base64 codec, MoE KV pages).
        snap = slot_state.SlotSnapshot.from_wire(r.snapshot).to_wire()
        resume.append(Request(p, g, snapshot=snap))
    res2 = B.run(resume, results=True)
    assert [r.tokens.tolist() for r in res2] == gold
    assert B.last_stats["migration_fallbacks"] == 0
    assert B.audit() == []


def test_moe_server_stats_and_wire(moe_model):
    """``server_stats.engine`` reports the expert knobs and a requests
    payload serves the MoE model over the wire."""
    from triton_distributed_tpu.serving.server import ModelServer, request

    eng = make_engine(moe_model)
    server = ModelServer(eng).start()
    try:
        stats = request(server.host, server.port, {"cmd": "stats"})
        e = stats["stats"]["server"]["engine"]
        assert e["num_experts"] == moe_model.cfg.num_experts
        assert e["experts_per_tok"] == moe_model.cfg.num_experts_per_tok
        out = request(server.host, server.port, {
            "requests": [PROMPTS[0].tolist()], "gen_lens": [4],
        })
        assert len(out["outputs"][0]) == 4
        assert out["stats"]["moe_routed_tokens"] > 0
        assert out["stats"]["a2a_dropped"] == 0
    finally:
        server.shutdown()


def test_moe_a2a_dropped_surface(moe_model):
    """The ``a2a_dropped`` ledger is a live surface, not a constant:
    the lossless serving path reports 0 by construction, and a
    capacity-mode EP run's detected overflow comes back through
    ``ep_moe_ffn(return_state=True)`` → ``DispatchState.num_dropped``
    (what perf/moe_serve_bench.py records)."""
    import functools

    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.moe.ep_a2a import ep_moe_ffn

    # Lossless serving arm: 0 by construction.
    eng = make_engine(moe_model)
    eng.run([(PROMPTS[0], 4)])
    assert eng.last_stats["a2a_dropped"] == 0

    # Capacity-mode arm (tp=1 shard_map): adversarial skew onto the
    # first experts at capacity_factor=1 must DROP and COUNT.
    rng = np.random.default_rng(3)
    e, d, f, k, t = 8, 32, 64, 2, 16
    x = jnp.asarray(np.abs(rng.standard_normal((t, d))) * 0.1,
                    jnp.float32)
    w_router = jnp.asarray(
        rng.standard_normal((d, e)) * 0.1, jnp.float32
    ).at[:, 2:].add(-100.0).at[:, :2].add(100.0)
    w1 = jnp.asarray(rng.standard_normal((e, d, 2 * f)) * 0.1,
                     jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((e, f, d)) * 0.1, jnp.float32)

    def body(x_loc):
        out, state = ep_moe_ffn(
            x_loc, w_router, w1, w2, k, capacity_factor=0.5,
            axis="tp", method="xla", return_state=True,
        )
        return out, state.num_dropped[None]

    fn = moe_model.ctx.shard_map(
        functools.partial(body),
        in_specs=P(None, None), out_specs=(P(None, None), P(None)),
    )
    _out, dropped = fn(x)
    assert int(np.asarray(dropped).sum()) > 0


def test_moe_cli_model_alias():
    """``--model moe`` resolves to the tiny-moe preset with the
    --num-experts/--top-k/--moe-intermediate overrides threaded through
    (the ONE resolution helper run_server's main uses)."""
    from triton_distributed_tpu.models.config import get_config
    from triton_distributed_tpu.serving.run_server import (
        resolve_model_args,
    )

    name, ov = resolve_model_args("moe", num_experts=4, top_k=2,
                                  moe_intermediate=32)
    assert name == "tiny-moe"
    cfg = get_config(name, **ov)
    assert cfg.num_experts == 4
    assert cfg.num_experts_per_tok == 2
    assert cfg.moe_intermediate_size == 32
    # Non-moe names pass through untouched.
    assert resolve_model_args("tiny") == ("tiny", {})
