"""bench.py tuned-config resolution: the sweep→ladder handoff contract.

The driver's end-of-round bench must apply a sweep-written
``perf/MEGA_TUNED.json`` only when it matches this chip AND model, must
honor an explicit env override, and must REFUSE (loudly) a malformed
override rather than silently timing defaults."""

import importlib.util
import json
import os

import pytest


@pytest.fixture
def bench(tmp_path, monkeypatch):
    """Load a COPY of bench.py from tmp_path so the tests' tuning file
    lives under tmp_path/perf/ — never the repo's real
    perf/MEGA_TUNED.json, which a live on-chip sweep may have written
    for the next bench round (and which pre-existing state would also
    break these tests)."""
    import shutil

    src = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")
    dst = tmp_path / "bench.py"
    shutil.copy(src, dst)
    (tmp_path / "perf").mkdir()
    spec = importlib.util.spec_from_file_location("bench_under_test", dst)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.delenv("TDT_BENCH_MEGA_CFG", raising=False)
    return mod


@pytest.fixture
def tuned_file(bench):
    path = os.path.join(
        os.path.dirname(os.path.abspath(bench.__file__)),
        "perf", "MEGA_TUNED.json",
    )

    def write(rec):
        with open(path, "w") as f:
            json.dump(rec, f)
        return path

    return write


def test_no_file_means_defaults(bench, tuned_file):
    cfg, note = bench._tuned_mega_config("TPU v5 lite", "Qwen/Qwen3-0.6B")
    assert cfg is None and "no tuning" in note


def test_matching_file_applies(bench, tuned_file):
    tuned_file({"config": "2048:1024:4", "device": "TPU v5 lite",
                "model": "Qwen/Qwen3-0.6B"})
    cfg, note = bench._tuned_mega_config("TPU v5 lite", "Qwen/Qwen3-0.6B")
    assert cfg.tile_n == 2048 and cfg.tile_k == 1024 and cfg.nbuf == 4
    assert "MEGA_TUNED" in note


@pytest.mark.parametrize("device,model", [
    ("TPU v4", "Qwen/Qwen3-0.6B"),          # other chip
    ("TPU v5 lite", "Qwen/Qwen3-0.6B+lite"),  # other geometry
])
def test_mismatched_file_ignored(bench, tuned_file, device, model):
    tuned_file({"config": "2048:1024:4", "device": "TPU v5 lite",
                "model": "Qwen/Qwen3-0.6B"})
    cfg, note = bench._tuned_mega_config(device, model)
    assert cfg is None and "defaults" in note


def test_env_override_wins(bench, tuned_file, monkeypatch):
    tuned_file({"config": "2048:1024:4", "device": "TPU v5 lite",
                "model": "m"})
    monkeypatch.setenv("TDT_BENCH_MEGA_CFG", "1024:1024:3")
    cfg, note = bench._tuned_mega_config("TPU v5 lite", "m")
    assert cfg.nbuf == 3 and "env" in note


def test_malformed_env_raises(bench, monkeypatch):
    monkeypatch.setenv("TDT_BENCH_MEGA_CFG", "2048:2048")
    with pytest.raises(ValueError, match="malformed"):
        bench._tuned_mega_config("TPU v5 lite", "m")


def test_malformed_file_ignored(bench, tuned_file):
    tuned_file({"config": "not-a-config", "device": "TPU v5 lite",
                "model": "m"})
    cfg, note = bench._tuned_mega_config("TPU v5 lite", "m")
    assert cfg is None and "malformed" in note


class TestProbeBudget:
    """Round-4 window strategy: probe-retry to the deadline, never zero
    probes, stop only when the budget truly ends (VERDICT r3 weak #1)."""

    def test_past_deadline_still_probes_once(self, bench, monkeypatch):
        calls = []
        monkeypatch.setattr(
            bench, "_probe_tpu_once", lambda: calls.append(1) or True
        )
        import time as _t

        assert bench._probe_tpu_until(_t.time() - 100) is True
        assert len(calls) == 1

    def test_retries_until_success(self, bench, monkeypatch):
        results = iter([False, False, True])
        calls = []
        monkeypatch.setattr(
            bench, "_probe_tpu_once",
            lambda: calls.append(1) or next(results),
        )
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        import time as _t

        assert bench._probe_tpu_until(_t.time() + 3600) is True
        assert len(calls) == 3

    def test_gives_up_at_deadline(self, bench, monkeypatch):
        monkeypatch.setattr(bench, "_probe_tpu_once", lambda: False)
        # Pin the sleep interval: an ambient TDT_BENCH_PROBE_SLEEP_S=0
        # would otherwise turn the "deadline closer than one sleep"
        # setup into a busy-spin to the deadline.
        monkeypatch.setattr(bench, "_PROBE_SLEEP_S", 20)
        slept = []
        monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
        import time as _t

        # Deadline closer than one sleep interval: one probe, no sleep.
        assert bench._probe_tpu_until(_t.time() + 1) is False
        assert not slept
