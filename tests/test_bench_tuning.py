"""bench.py tuned-config resolution: the sweep→ladder handoff contract.

The driver's end-of-round bench must apply a sweep-written
``perf/MEGA_TUNED.json`` only when it matches this chip AND model, must
honor an explicit env override, and must REFUSE (loudly) a malformed
override rather than silently timing defaults."""

import importlib.util
import json
import os

import pytest


@pytest.fixture
def bench(tmp_path, monkeypatch):
    """Load a COPY of bench.py from tmp_path so the tests' tuning file
    lives under tmp_path/perf/ — never the repo's real
    perf/MEGA_TUNED.json, which a live on-chip sweep may have written
    for the next bench round (and which pre-existing state would also
    break these tests)."""
    import shutil

    src = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")
    dst = tmp_path / "bench.py"
    shutil.copy(src, dst)
    (tmp_path / "perf").mkdir()
    spec = importlib.util.spec_from_file_location("bench_under_test", dst)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.delenv("TDT_BENCH_MEGA_CFG", raising=False)
    return mod


@pytest.fixture
def tuned_file(bench):
    path = os.path.join(
        os.path.dirname(os.path.abspath(bench.__file__)),
        "perf", "MEGA_TUNED.json",
    )

    def write(rec):
        with open(path, "w") as f:
            json.dump(rec, f)
        return path

    return write


def test_no_file_means_defaults(bench, tuned_file):
    cfg, note = bench._tuned_mega_config("TPU v5 lite", "Qwen/Qwen3-0.6B")
    assert cfg is None and "no tuning" in note


def test_matching_file_applies(bench, tuned_file):
    tuned_file({"config": "2048:1024:4", "device": "TPU v5 lite",
                "model": "Qwen/Qwen3-0.6B"})
    cfg, note = bench._tuned_mega_config("TPU v5 lite", "Qwen/Qwen3-0.6B")
    assert cfg.tile_n == 2048 and cfg.tile_k == 1024 and cfg.nbuf == 4
    assert "MEGA_TUNED" in note


@pytest.mark.parametrize("device,model", [
    ("TPU v4", "Qwen/Qwen3-0.6B"),          # other chip
    ("TPU v5 lite", "Qwen/Qwen3-0.6B+lite"),  # other geometry
])
def test_mismatched_file_ignored(bench, tuned_file, device, model):
    tuned_file({"config": "2048:1024:4", "device": "TPU v5 lite",
                "model": "Qwen/Qwen3-0.6B"})
    cfg, note = bench._tuned_mega_config(device, model)
    assert cfg is None and "defaults" in note


def test_env_override_wins(bench, tuned_file, monkeypatch):
    tuned_file({"config": "2048:1024:4", "device": "TPU v5 lite",
                "model": "m"})
    monkeypatch.setenv("TDT_BENCH_MEGA_CFG", "1024:1024:3")
    cfg, note = bench._tuned_mega_config("TPU v5 lite", "m")
    assert cfg.nbuf == 3 and "env" in note


def test_malformed_env_raises(bench, monkeypatch):
    monkeypatch.setenv("TDT_BENCH_MEGA_CFG", "2048:2048")
    with pytest.raises(ValueError, match="malformed"):
        bench._tuned_mega_config("TPU v5 lite", "m")


def test_malformed_file_ignored(bench, tuned_file):
    tuned_file({"config": "not-a-config", "device": "TPU v5 lite",
                "model": "m"})
    cfg, note = bench._tuned_mega_config("TPU v5 lite", "m")
    assert cfg is None and "malformed" in note


class TestProbeBudget:
    """Round-5 window strategy: probe-retry to the deadline with a
    PRE-probe deadline check — a probe that cannot finish before the
    reserve boundary is never started, so the CPU reserve is a true
    reserve (VERDICT r4 weak #1a overruled r4's probe-first rule; the
    healthy-TPU-never-skipped property now lives in the emit-first
    minimal line plus the worker loop's guaranteed attempt 0)."""

    def test_past_deadline_never_probes(self, bench, monkeypatch):
        calls = []
        monkeypatch.setattr(
            bench, "_probe_tpu_once", lambda: calls.append(1) or True
        )
        import time as _t

        assert bench._probe_tpu_until(_t.time() - 100) is False
        assert not calls

    def test_no_probe_started_that_cannot_finish(self, bench, monkeypatch):
        calls = []
        monkeypatch.setattr(
            bench, "_probe_tpu_once", lambda: calls.append(1) or True
        )
        monkeypatch.setattr(bench, "_PROBE_TIMEOUT_S", 180)
        import time as _t

        # 100 s of budget < one 180 s probe: zero probes, no overrun.
        assert bench._probe_tpu_until(_t.time() + 100) is False
        assert not calls

    def test_retries_until_success(self, bench, monkeypatch):
        results = iter([False, False, True])
        calls = []
        monkeypatch.setattr(
            bench, "_probe_tpu_once",
            lambda: calls.append(1) or next(results),
        )
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        import time as _t

        assert bench._probe_tpu_until(_t.time() + 3600) is True
        assert len(calls) == 3

    def test_gives_up_without_burning_reserve(self, bench, monkeypatch):
        probes = []
        monkeypatch.setattr(
            bench, "_probe_tpu_once", lambda: probes.append(1) or False
        )
        monkeypatch.setattr(bench, "_PROBE_SLEEP_S", 20)
        monkeypatch.setattr(bench, "_PROBE_TIMEOUT_S", 180)
        slept = []
        monkeypatch.setattr(bench.time, "sleep", lambda s: slept.append(s))
        import time as _t

        # Budget fits exactly one probe (probe mocked instant): one
        # attempt, then no sleep-and-retry that would overrun.
        assert bench._probe_tpu_until(_t.time() + 200) is False
        assert len(probes) == 1
        assert not slept


class TestEmitFirst:
    """VERDICT r4 next #1: the driver artifact must be unloseable. A
    bench run whose deadline is already inside (or past) the CPU
    reserve must STILL print a parseable JSON line — immediately, with
    the newest cached on-chip ladder attached — before attempting any
    refinement."""

    def _run_bench(self, env_extra, timeout=120):
        import subprocess
        import sys

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env.update(env_extra)
        return subprocess.run(
            [sys.executable, os.path.join(root, "bench.py")],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=root,
        )

    def test_all_down_past_deadline_still_emits(self, tmp_path):
        # Deadline (70 s) − reserve (480 s) < 0: zero probes; stub
        # budget < 120 s: stub skipped. The minimal line must parse.
        # Private lock path: the live relay watcher may hold the real
        # chip lock mid-window, and this test must not wait on it.
        r = self._run_bench({
            "TDT_BENCH_DEADLINE_S": "70",
            "TDT_TPU_LOCK": str(tmp_path / "tpu.lock"),
        })
        lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
        assert lines, f"no stdout; stderr: {r.stderr[-500:]}"
        out = json.loads(lines[-1])
        assert out["metric"] == "qwen3_decode_ms_per_step"
        assert out["value"] is None
        assert out["platform"] == "cpu"
        assert out["unit"] == "ms"
        # The repo carries a real round-3 on-chip ladder in
        # perf/ONCHIP_r3.jsonl — the minimal line must surface it,
        # labeled as cached.
        cached = out.get("last_known_tpu")
        if cached is not None:
            assert "CACHED" in cached["note"]
            assert cached["result"]["platform"] == "tpu"
            assert "ladder" in cached["result"]

    @pytest.mark.slow
    def test_forced_probe_drives_worker_orchestration(self, tmp_path):
        """TDT_BENCH_FORCE_PROBE=ok on a TPU-less host sends main()
        down the REAL TPU-worker path: the worker hangs in init
        exactly like a wedged relay, the watchdog kills it, the +lite
        fallback fires, and the fallback output is labeled 'relay
        answered' (not 'relay down') with the init stalls surfaced in
        tpu_errors. This machinery otherwise only ever runs against a
        live chip — where it failed in novel ways three rounds
        straight — so it gets an offline e2e drive here."""
        # Deterministic wedge: the worker parks at start:init (no jax,
        # no chip contact) so the test is independent of relay state,
        # host speed, and memory. Probe timeout 10 s keeps the forced
        # probes inside the pre-probe deadline check; test timeout
        # (600 s) exceeds the bench deadline (560 s) so bench always
        # finishes (or is internally bounded) before the test kills it.
        r = self._run_bench({
            "TDT_BENCH_DEADLINE_S": "560",
            "TDT_BENCH_PROBE_TIMEOUT_S": "10",
            "TDT_BENCH_FORCE_PROBE": "ok",
            "TDT_BENCH_FORCE_WORKER_HANG": "1",
            "TDT_BENCH_INIT_TIMEOUT_S": "15",
            "TDT_BENCH_WORKER_ATTEMPTS": "2",
            "TDT_TPU_LOCK": str(tmp_path / "tpu.lock"),
        }, timeout=600)
        lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
        assert lines, f"no stdout; stderr: {r.stderr[-800:]}"
        parsed = [json.loads(ln) for ln in lines]
        first = parsed[0]
        assert first["value"] is None
        assert "relay answered" in first["note"]
        assert "init stalled" in first["tpu_errors"]["init"]
        # The full-model init wedge must have triggered the +lite drop.
        assert "falling back to" in r.stderr
        # The refined stub line (if the budget allowed it) must carry
        # the same relay-answered labeling.
        if len(parsed) > 1 and parsed[-1]["value"] is not None:
            assert "relay answered" in parsed[-1]["note"]
            assert "init stalled" in parsed[-1]["tpu_errors"]["init"]

    @pytest.mark.slow
    def test_all_down_stub_refines_minimal_line(self, tmp_path):
        """With enough tail budget the CPU stub must land a SECOND
        line with a real measurement that supersedes the minimal one
        (the driver parses the last JSON line)."""
        # Deadline 490 s: probe budget (10 s) < one probe, so no
        # probes; stub budget ≈ 430 s fits the (cache-warmed) stub.
        r = self._run_bench({
            "TDT_BENCH_DEADLINE_S": "490",
            "TDT_TPU_LOCK": str(tmp_path / "tpu.lock"),
        }, timeout=480)
        lines = [ln for ln in r.stdout.splitlines() if ln.strip()]
        parsed = [json.loads(ln) for ln in lines]
        assert len(parsed) >= 2, f"want minimal+refined; got {lines}"
        assert parsed[0]["value"] is None  # minimal, emitted first
        refined = parsed[-1]
        assert refined["platform"] == "cpu"
        assert isinstance(refined["value"], float)
        assert refined["metric"] == "qwen3_tiny_decode_ms_per_step"
        assert "CPU fallback stub" in refined["note"]

    def test_last_known_tpu_picks_newest(self, bench):
        perf = os.path.join(
            os.path.dirname(os.path.abspath(bench.__file__)), "perf"
        )
        older = {"step": "ladder", "t_start": 100.0, "rc": 0,
                 "stdout_tail": json.dumps(
                     {"platform": "tpu", "ladder": {"jit": 9.0}}) + "\n"}
        cpu_rec = {"step": "ladder", "t_start": 300.0, "rc": 0,
                   "stdout_tail": json.dumps(
                       {"platform": "cpu", "ladder": {"jit": 240.0}}) + "\n"}
        newer = {"step": "ladder", "t_start": 200.0, "rc": 0,
                 "stdout_tail": "noise line\n" + json.dumps(
                     {"platform": "tpu", "ladder": {"mega": 4.3}}) + "\n"}
        with open(os.path.join(perf, "ONCHIP_r0.jsonl"), "w") as f:
            for rec in (older, cpu_rec, newer):
                f.write(json.dumps(rec) + "\n")
        got = bench._last_known_tpu()
        assert got is not None
        assert got["result"]["ladder"] == {"mega": 4.3}
        assert got["source"].endswith(":ladder")
        assert "CACHED" in got["note"]
