"""Megakernel tests: scheduler, task table, and full Qwen3 decode parity.

Parity model (SURVEY.md §4): the reference validates its megakernel
against the torch forward (``mega_triton_kernel/test/models/test_qwen3.py``);
here the golden is the XLA decode path of the same ``Qwen3``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.megakernel import (
    MegaQwen3,
    SchedulePolicy,
    Task,
    TaskDependency,
    TaskType,
    pack_table,
    schedule,
)
from triton_distributed_tpu.models import AutoLLM


def _t(tid, typ, deps=(), layer=0):
    return Task(
        task_id=tid, task_type=typ, layer_id=layer,
        deps=tuple(TaskDependency(d) for d in deps),
    )


class TestScheduler:
    def test_round_robin_keeps_order(self):
        tasks = [
            _t(0, TaskType.EMBED),
            _t(1, TaskType.NORM, deps=[0]),
            _t(2, TaskType.QKV_PROJ, deps=[1]),
        ]
        order = schedule(tasks, SchedulePolicy.ROUND_ROBIN)
        assert [t.task_id for t in order] == [0, 1, 2]

    def test_deps_respected_any_policy(self):
        # Diamond: 0 → {1, 2} → 3
        tasks = [
            _t(0, TaskType.EMBED),
            _t(1, TaskType.NORM, deps=[0]),
            _t(2, TaskType.ALLREDUCE, deps=[0]),
            _t(3, TaskType.LM_HEAD, deps=[1, 2]),
        ]
        for pol in SchedulePolicy:
            order = [t.task_id for t in schedule(tasks, pol)]
            assert order.index(0) < order.index(1)
            assert order.index(0) < order.index(2)
            assert order.index(3) == 3

    def test_zigzag_interleaves_classes(self):
        # Independent compute + comm tasks: zig-zag alternates them.
        tasks = [
            _t(0, TaskType.NORM),
            _t(1, TaskType.QKV_PROJ),
            _t(2, TaskType.BARRIER),
            _t(3, TaskType.ALLREDUCE),
        ]
        order = [t.task_type for t in schedule(tasks, SchedulePolicy.ZIG_ZAG)]
        assert order[0] == TaskType.NORM
        assert order[1] in (TaskType.BARRIER, TaskType.ALLREDUCE)

    def test_cycle_detected(self):
        tasks = [_t(0, TaskType.NORM, deps=[1]), _t(1, TaskType.NORM, deps=[0])]
        with pytest.raises(ValueError, match="cycle"):
            schedule(tasks)

    def test_unknown_dep_rejected(self):
        with pytest.raises(ValueError, match="unknown"):
            schedule([_t(0, TaskType.NORM, deps=[7])])

    def test_pack_table_headers(self):
        tasks = [_t(0, TaskType.ATTN, layer=3)]
        tab = pack_table(tasks)
        assert tab.shape == (1, 8)
        assert tab[0, 0] == int(TaskType.ATTN)
        assert tab[0, 1] == 3


class TestMegaQwen3:
    @pytest.mark.parametrize(
        "policy", [SchedulePolicy.ROUND_ROBIN, SchedulePolicy.ZIG_ZAG]
    )
    @pytest.mark.slow
    def test_decode_parity_tp4(self, ctx4, policy):
        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        B = 2
        cache = model.new_cache(B, max_length=64)

        # Populate a few positions through the golden path.
        step_gold = model.decode_fn("xla")
        toks = jnp.asarray([[3, 5], [7, 11], [13, 17]], jnp.int32)
        for i in range(toks.shape[0]):
            _, cache = step_gold(model.params, toks[i], cache)

        tok = jnp.asarray([19, 23], jnp.int32)
        logits_gold, cache_gold = step_gold(model.params, tok, cache)

        mega = MegaQwen3(model, policy=policy)
        cache_in = jax.tree.map(jnp.copy, cache)
        logits_mega, cache_mega = mega.decode_step(tok, cache_in)

        np.testing.assert_allclose(
            np.asarray(logits_mega), np.asarray(logits_gold),
            rtol=2e-3, atol=2e-3,
        )
        np.testing.assert_allclose(
            np.asarray(cache_mega.k), np.asarray(cache_gold.k),
            rtol=2e-3, atol=2e-3,
        )
        np.testing.assert_allclose(
            np.asarray(cache_mega.v), np.asarray(cache_gold.v),
            rtol=2e-3, atol=2e-3,
        )
        np.testing.assert_array_equal(
            np.asarray(cache_mega.kv_len), np.asarray(cache_gold.kv_len)
        )

    def test_task_graph_shape(self, ctx4):
        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        mega = MegaQwen3(model)
        compiled, _, _ = mega.build(1, 64)
        L = model.cfg.num_layers
        # entry barrier (tp>1) + embed + 9 per layer + final norm + lm_head
        assert compiled.num_tasks == 1 + 1 + 9 * L + 2
        types = {t.task_type for t in compiled.order}
        assert TaskType.ALLREDUCE in types and TaskType.ATTN in types
        assert compiled.order[0].task_type == TaskType.BARRIER


class TestMegaPaged:
    @pytest.mark.parametrize("s_max", [64, 128])  # 128: pick_tile's 128
    # floor must not widen s_blk past the 16-wide page
    @pytest.mark.slow
    def test_decode_parity_paged(self, ctx4, s_max):
        """Megakernel over a paged pool (table-indexed block DMAs) vs
        the dense XLA golden (parity: reference megakernel paged decode,
        mega_triton_kernel/models/paged_kv_cache.py)."""
        from triton_distributed_tpu.models.paged_kv_cache import (
            as_dense,
            init_paged_cache,
            write_prefill,
        )

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        B, page = 2, 16

        # Golden path: dense cache, a few decode steps for context.
        cache = model.new_cache(B, max_length=s_max)
        step_gold = model.decode_fn("xla")
        toks = jnp.asarray([[3, 5], [7, 11], [13, 17]], jnp.int32)
        for i in range(toks.shape[0]):
            _, cache = step_gold(model.params, toks[i], cache)

        # Mirror that context into pages (one write_prefill per row).
        paged, _pool = init_paged_cache(
            model.cfg, B, ctx4, max_length=s_max, page_size=page
        )
        for b in range(B):
            row = jax.tree.map(lambda x: x[:, b:b + 1], 
                               {"k": cache.k, "v": cache.v})
            paged = write_prefill(
                paged, b, row["k"], row["v"], int(cache.kv_len[b])
            )

        tok = jnp.asarray([19, 23], jnp.int32)
        logits_gold, cache_gold = step_gold(model.params, tok, cache)

        mega = MegaQwen3(model)
        logits_mega, paged_out = mega.decode_step(tok, paged)

        np.testing.assert_allclose(
            np.asarray(logits_mega), np.asarray(logits_gold),
            rtol=2e-3, atol=2e-3,
        )
        k_dense, v_dense = as_dense(paged_out)
        np.testing.assert_allclose(
            np.asarray(k_dense), np.asarray(cache_gold.k),
            rtol=2e-3, atol=2e-3,
        )
        np.testing.assert_array_equal(
            np.asarray(paged_out.kv_len), np.asarray(cache_gold.kv_len)
        )

    @pytest.mark.slow
    def test_paged_decode_fn_qwen(self, ctx4):
        """Model-level paged decode (paged_flash_decode path) matches
        the dense decode step."""
        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        from triton_distributed_tpu.models.paged_kv_cache import (
            init_paged_cache,
        )

        B = 2
        cache = model.new_cache(B, max_length=64)
        paged, _pool = init_paged_cache(
            model.cfg, B, ctx4, max_length=64, page_size=16
        )
        toks = jnp.asarray([[3, 5], [7, 11], [19, 23]], jnp.int32)
        for i in range(toks.shape[0]):
            logits_d, cache = model.decode_step(toks[i], cache, "xla")
            logits_p, paged = model.decode_step(toks[i], paged, "xla")
            np.testing.assert_allclose(
                np.asarray(logits_p), np.asarray(logits_d),
                rtol=2e-3, atol=2e-3,
            )


class TestMegaPrefill:
    def test_prefill_parity(self, ctx4):
        """Megakernel prefill (causal self-attn tasks, LOAD_X entry,
        last-row LM head) vs the model's XLA prefill: logits + cache
        must match (parity: reference prefill TaskBuilders,
        model_builder.py:189-352)."""
        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        S, true_len = 16, 13  # right-padded prompt
        toks = jnp.asarray(np.arange(S) % 251 + 1, jnp.int32)

        cache_g = model.new_cache(1, max_length=64)
        logits_g, cache_g = model.prefill(
            toks, cache_g, "xla", true_len=true_len
        )

        mega = MegaQwen3(model)
        cache_m = model.new_cache(1, max_length=64)
        logits_m, cache_m = mega.prefill(toks, cache_m, true_len=true_len)

        np.testing.assert_allclose(
            np.asarray(logits_m), np.asarray(logits_g), rtol=2e-3, atol=2e-3
        )
        # Cache parity on the real positions only (pads diverge and are
        # masked by kv_len downstream).
        np.testing.assert_allclose(
            np.asarray(cache_m.k)[:, :, :, :true_len],
            np.asarray(cache_g.k)[:, :, :, :true_len],
            rtol=2e-3, atol=2e-3,
        )
        np.testing.assert_array_equal(
            np.asarray(cache_m.kv_len), np.asarray(cache_g.kv_len)
        )

    @pytest.mark.slow
    def test_prefill_then_mega_decode(self, ctx4):
        """Greedy continuation after a mega prefill matches the XLA
        path end-to-end."""
        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        toks = jnp.asarray([5, 9, 2, 4, 8, 6, 7, 3], jnp.int32)

        cache_g = model.new_cache(1, max_length=64)
        logits_g, cache_g = model.prefill(toks, cache_g, "xla")
        mega = MegaQwen3(model)
        cache_m = model.new_cache(1, max_length=64)
        logits_m, cache_m = mega.prefill(toks, cache_m)

        tok_g = jnp.argmax(logits_g)[None].astype(jnp.int32)
        tok_m = jnp.argmax(logits_m)[None].astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(tok_g), np.asarray(tok_m))
        step = model.decode_fn("xla")
        for _ in range(3):
            lg, cache_g = step(model.params, tok_g, cache_g)
            lm, cache_m = mega.decode_step(tok_m, cache_m)
            tok_g = jnp.argmax(lg, -1).astype(jnp.int32)
            tok_m = jnp.argmax(lm, -1).astype(jnp.int32)
            np.testing.assert_array_equal(np.asarray(tok_g), np.asarray(tok_m))


@pytest.mark.slow
def test_lm_head_remainder_tile(ctx4):
    """Wide LM tiles on an unround vocab: tn_lm = tile_n with a final
    remainder tile (per-shard vocab 384, tile 256 → rem 128) must match the
    golden decode step."""
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, vocab_size=1536)
    cache = model.new_cache(1, max_length=64)
    step_gold = model.decode_fn("xla")
    for t in (3, 5):
        _, cache = step_gold(model.params, jnp.asarray([t], jnp.int32), cache)

    tok = jnp.asarray([7], jnp.int32)
    logits_gold, _ = step_gold(model.params, tok, jax.tree.map(jnp.copy, cache))

    mega = MegaQwen3(model, cfg=MegaConfig(tile_n=256))
    built = mega._built(1, 64)[0]
    from triton_distributed_tpu.megakernel.code_generator import MegaDims
    resolved = mega.cfg.resolve(mega._dims(1, 64))
    assert resolved.tn_lm == 256  # wide tile, not pick_tile's 128
    assert 1536 // 4 % 256 == 128  # the tail this test exercises

    logits_mega, _ = mega.decode_step(tok, cache)
    np.testing.assert_allclose(
        np.asarray(logits_mega), np.asarray(logits_gold),
        rtol=2e-3, atol=2e-3,
    )


@pytest.mark.slow
@pytest.mark.parametrize("fused", [True, False])
def test_cross_prefetch_parity(ctx4, fused):
    """cross_prefetch (the previous task starts the next task's first
    weight-tile DMA; the stream consumes the SMEM flag and skips its
    duplicate start) must be token-exact INCLUDING multi-step launches
    — the flag handoff must also stop cleanly at each step's last task
    (the next grid iteration is the next step's EMBED). The unfused
    variant covers NORM-preceded stream boundaries."""
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    cache = model.new_cache(1, max_length=64)
    step_gold = model.decode_fn("xla")
    for t in (3, 5):
        _, cache = step_gold(model.params, jnp.asarray([t], jnp.int32), cache)
    tok = jnp.asarray([7], jnp.int32)
    logits_gold, _ = step_gold(model.params, tok, jax.tree.map(jnp.copy, cache))

    # Golden 3-token greedy chain from the xla step.
    gtok, gc, gold_chain = tok, jax.tree.map(jnp.copy, cache), []
    for _ in range(3):
        lg, gc = step_gold(model.params, gtok, gc)
        gtok = jnp.argmax(lg, -1).astype(jnp.int32)
        gold_chain.append(int(gtok[0]))

    mega = MegaQwen3(
        model, cfg=MegaConfig(fuse_norms=fused, cross_prefetch=True)
    )
    logits_mega, _ = mega.decode_step(tok, jax.tree.map(jnp.copy, cache))
    np.testing.assert_allclose(
        np.asarray(logits_mega), np.asarray(logits_gold),
        rtol=2e-3, atol=2e-3,
    )
    # Multi-step launch: 3 steps in one kernel, prefetch flags crossing
    # the step boundary.
    mm = mega.decode_multi_fn(1, 64, 3)
    toks3, _, _ = mm(model.params, tok, cache)
    assert [int(x) for x in np.asarray(toks3)[:, 0]] == gold_chain


@pytest.mark.slow
@pytest.mark.parametrize("extras", [
    {},
    # The full tuned q8 stack the on-chip sweep runs (deep staging +
    # fused norms + cross-task prefetch over int8 streams).
    {"nbuf": 3, "fuse_norms": True, "cross_prefetch": True},
])
def test_wq8_parity_vs_dequant_gold(ctx4, extras):
    """Weight-only int8 decode (MegaConfig.wq8): the megakernel fed
    Q8Params must match an XLA forward over the DEQUANTIZED weights
    (same math up to bf16 rounding order — the golden rounds w8·scale
    to bf16 before its dots, the kernel scales the f32 product; row
    shards dequantize per rank before the allreduce in both), and the
    multi-step greedy chain must be token-exact against that golden."""
    import dataclasses as dc

    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.megakernel.code_generator import MegaConfig

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, max_length=64)
    cache = model.new_cache(1)
    toks = jnp.asarray(np.arange(16) % model.cfg.vocab_size, jnp.int32)
    logits, cache = model.prefill(toks, cache, "xla")
    tok0 = jnp.argmax(logits)[None].astype(jnp.int32)
    clone = lambda c: jax.tree.map(jnp.copy, c)  # noqa: E731

    mega = MegaQwen3(model, cfg=MegaConfig(wq8=True, **extras))
    qp = mega.quantized_params()
    assert qp.wqkv.dtype == jnp.int8 and qp.lm_head.dtype == jnp.int8

    ctx = model.ctx
    dt = model.cfg.dtype

    def deq(spec):
        return ctx.shard_map(
            lambda w8, s: (w8.astype(jnp.float32) * s).astype(dt),
            in_specs=(spec, spec), out_specs=spec,
        )

    col3, row3, col2 = P(None, None, "tp"), P(None, "tp", None), P(None, "tp")
    lp = model.params.layers
    gold_params = dc.replace(
        model.params,
        layers=dc.replace(
            lp,
            attn=dc.replace(lp.attn, wqkv=deq(col3)(qp.wqkv, qp.sc_qkv),
                            wo=deq(row3)(qp.wo, qp.sc_o)),
            mlp=dc.replace(lp.mlp, w1=deq(col3)(qp.w1, qp.sc_w1),
                           w2=deq(row3)(qp.w2, qp.sc_w2)),
        ),
        lm_head=deq(col2)(qp.lm_head, qp.sc_lm),
    )
    gold_step = model.decode_fn("xla")
    lg_gold, _ = jax.jit(gold_step)(gold_params, tok0, clone(cache))
    lg_mega, _ = mega.decode_fn(1, 64)(qp, tok0, clone(cache))
    np.testing.assert_allclose(
        np.asarray(lg_mega), np.asarray(lg_gold), rtol=2e-3, atol=2e-3,
    )

    # Multi-step greedy: token-exact vs the dequant golden chain.
    tok, c, ref = tok0, clone(cache), []
    for _ in range(3):
        lg, c = jax.jit(gold_step)(gold_params, tok, c)
        tok = jnp.argmax(lg, -1).astype(jnp.int32)
        ref.append(int(tok[0]))
    t3, _, _ = mega.decode_multi_fn(1, 64, 3)(qp, tok0, cache)
    assert [int(x) for x in np.asarray(t3)[:, 0]] == ref


def test_cross_prefetch_needs_depth(ctx4):
    from triton_distributed_tpu.megakernel.code_generator import (
        MegaConfig,
        MegaDims,
    )

    with pytest.raises(ValueError, match="nbuf >= 2"):
        MegaConfig(nbuf=1, cross_prefetch=True).resolve(
            MegaDims(batch=1, d=128, hq_loc=1, hkv_loc=1, head_dim=128,
                     f_loc=128, v_loc=128, num_layers=1, s_max=64,
                     n_ranks=1, rms_eps=1e-6, rope_theta=1e6)
        )


def test_fused_norms_parity(ctx4):
    """fuse_norms folds the RMS norms into qkv/fc1/lm_head (dropping
    2 tasks/layer + the final norm from the grid) — must be
    logits-exact vs the golden step, with the task count shrunk by
    exactly the removed norms."""
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
    cache = model.new_cache(1, max_length=64)
    step_gold = model.decode_fn("xla")
    for t in (3, 5):
        _, cache = step_gold(model.params, jnp.asarray([t], jnp.int32), cache)
    tok = jnp.asarray([7], jnp.int32)
    logits_gold, _ = step_gold(model.params, tok, jax.tree.map(jnp.copy, cache))

    base = MegaQwen3(model)
    fused = MegaQwen3(model, cfg=MegaConfig(fuse_norms=True))
    n_base = len(base._built(1, 64)[0].order)
    n_fused = len(fused._built(1, 64)[0].order)
    L = model.cfg.num_layers
    assert n_base - n_fused == 2 * L + 1  # per-layer ln1+ln2, final norm

    logits_mega, _ = fused.decode_step(tok, cache)
    np.testing.assert_allclose(
        np.asarray(logits_mega), np.asarray(logits_gold),
        rtol=2e-3, atol=2e-3,
    )

    # Fused PREFILL graph too (inline final norm feeds the lm_head's
    # onehot row select — a distinct composition from decode).
    prompt = jnp.asarray([3, 5, 7, 2], jnp.int32)
    gold_pre, _ = model.prefill(prompt, model.new_cache(1, max_length=64),
                                "xla")
    mega_pre, _ = fused.prefill(prompt, model.new_cache(1, max_length=64))
    np.testing.assert_allclose(
        np.asarray(mega_pre), np.asarray(gold_pre), rtol=2e-3, atol=2e-3,
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "nbuf",
    [
        3,
        # One non-default depth in the fast path is enough coverage of
        # the generalized pipeline; the other depths (incl. the nbuf=1
        # serial degenerate) are heavyweight repeats of the same paths.
        pytest.param(1, marks=pytest.mark.slow),
        pytest.param(4, marks=pytest.mark.slow),
    ],
)
def test_deep_weight_stream_pipeline(ctx4, nbuf):
    """nbuf != 2 staging (depth-nbuf weight-stream pipeline, the HBM
    floor lever on chip) must be logits-exact vs the golden step —
    covers the prologue fill, the depth-1-ahead prefetch, and the tail
    tile joining a deeper rotation."""
    from triton_distributed_tpu.megakernel.code_generator import MegaConfig

    model = AutoLLM.from_pretrained("tiny", ctx=ctx4, vocab_size=1536)
    cache = model.new_cache(1, max_length=64)
    step_gold = model.decode_fn("xla")
    for t in (3, 5):
        _, cache = step_gold(model.params, jnp.asarray([t], jnp.int32), cache)
    tok = jnp.asarray([7], jnp.int32)
    logits_gold, _ = step_gold(model.params, tok, jax.tree.map(jnp.copy, cache))

    # tile 256 on the 384-wide per-shard vocab → one main tile + a
    # 128-wide TAIL tile, with the stream shorter than the pipeline at
    # nbuf=4 — exercises the prologue covering the whole stream AND the
    # tail joining a deeper slot rotation (the trickiest new paths).
    mega = MegaQwen3(model, cfg=MegaConfig(tile_n=256, nbuf=nbuf))
    logits_mega, _ = mega.decode_step(tok, cache)
    np.testing.assert_allclose(
        np.asarray(logits_mega), np.asarray(logits_gold),
        rtol=2e-3, atol=2e-3,
    )


@pytest.fixture
def ctx1():
    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    yield ctx
    mesh_mod.finalize_distributed()


class TestMultiStepDecode:
    """Multi-step greedy decode: nsteps whole steps in one kernel launch
    (in-kernel argmax + SMEM token feedback + knew/vnew band)."""

    @pytest.mark.slow
    def test_multi_matches_chained_single(self, ctx1):
        model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
        B, NS = 2, 4
        cache = model.new_cache(B, max_length=64)
        step_gold = model.decode_fn("xla")
        warm = jnp.asarray([[3, 5], [7, 11], [13, 17]], jnp.int32)
        for i in range(warm.shape[0]):
            _, cache = step_gold(model.params, warm[i], cache)

        mega = MegaQwen3(model)
        s_max = int(cache.k.shape[3])
        tok0 = jnp.asarray([19, 23], jnp.int32)

        # Reference: chained single-step mega with argmax outside.
        step = mega.decode_fn(B, s_max)
        t, c = tok0, jax.tree.map(jnp.copy, cache)
        ref_toks = []
        for _ in range(NS):
            lg, c = step(model.params, t, c)
            t = jnp.argmax(lg, -1).astype(jnp.int32)
            ref_toks.append(np.asarray(t))
        ref_logits = np.asarray(lg)

        multi = mega.decode_multi_fn(B, s_max, NS)
        mtoks, mlogits, mc = multi(
            model.params, tok0, jax.tree.map(jnp.copy, cache)
        )
        np.testing.assert_array_equal(
            np.asarray(mtoks), np.stack(ref_toks)
        )
        np.testing.assert_allclose(
            np.asarray(mlogits), ref_logits, rtol=2e-3, atol=2e-3
        )
        np.testing.assert_allclose(
            np.asarray(mc.k), np.asarray(c.k), rtol=2e-3, atol=2e-3
        )
        np.testing.assert_array_equal(
            np.asarray(mc.kv_len), np.asarray(c.kv_len)
        )

    @pytest.mark.slow
    def test_multi_matches_chained_single_tp4(self, ctx4):
        """Under TP the LM head's local argmax is cross-rank exchanged;
        tokens must still match chained single-step decode exactly."""
        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        B, NS = 2, 3
        cache = model.new_cache(B, max_length=64)
        step_gold = model.decode_fn("xla")
        warm = jnp.asarray([[3, 5], [7, 11]], jnp.int32)
        for i in range(warm.shape[0]):
            _, cache = step_gold(model.params, warm[i], cache)

        mega = MegaQwen3(model)
        s_max = int(cache.k.shape[3])
        tok0 = jnp.asarray([19, 23], jnp.int32)

        step = mega.decode_fn(B, s_max)
        t, c = tok0, jax.tree.map(jnp.copy, cache)
        ref_toks = []
        for _ in range(NS):
            lg, c = step(model.params, t, c)
            t = jnp.argmax(lg, -1).astype(jnp.int32)
            ref_toks.append(np.asarray(t))

        multi = mega.decode_multi_fn(B, s_max, NS)
        mtoks, _, mc = multi(
            model.params, tok0, jax.tree.map(jnp.copy, cache)
        )
        np.testing.assert_array_equal(np.asarray(mtoks), np.stack(ref_toks))
        np.testing.assert_allclose(
            np.asarray(mc.k), np.asarray(c.k), rtol=2e-3, atol=2e-3
        )

    @pytest.mark.slow
    @pytest.mark.parametrize("nranks", [1, 4])
    def test_multi_sampled_gumbel(self, request, nranks):
        """Sampled multi-step (argmax over logits + host-drawn noise)
        matches the host chaining tokens exactly — Gumbel-max
        temperature sampling with JAX-land RNG."""
        from triton_distributed_tpu.runtime import mesh as mesh_mod

        if nranks == 1:
            ctx = mesh_mod.initialize_distributed(
                tp=1, devices=jax.devices()[:1]
            )
        else:
            ctx = mesh_mod.initialize_distributed(
                tp=4, devices=jax.devices()[:4]
            )
        try:
            model = AutoLLM.from_pretrained("tiny", ctx=ctx)
            B, NS = 2, 3
            cache = model.new_cache(B, max_length=64)
            step_gold = model.decode_fn("xla")
            _, cache = step_gold(
                model.params, jnp.asarray([3, 5], jnp.int32), cache
            )
            mega = MegaQwen3(model)
            s_max = int(cache.k.shape[3])
            tok0 = jnp.asarray([19, 23], jnp.int32)
            V = model.cfg.vocab_size
            v_pad = model.params.lm_head.shape[1]
            temp = 0.7
            noise = temp * jax.random.gumbel(
                jax.random.key(7), (NS, B, v_pad), jnp.float32
            )

            # Host reference: chained single-step mega + noisy argmax.
            step = mega.decode_fn(B, s_max)
            t, c = tok0, jax.tree.map(jnp.copy, cache)
            ref_toks = []
            for i in range(NS):
                lg, c = step(model.params, t, c)
                t = jnp.argmax(
                    lg + noise[i, :, :V], -1
                ).astype(jnp.int32)
                ref_toks.append(np.asarray(t))

            fn = mega.decode_multi_fn(B, s_max, NS, sampled=True)
            mtoks, _, _ = fn(
                model.params, tok0, jax.tree.map(jnp.copy, cache), noise
            )
            np.testing.assert_array_equal(
                np.asarray(mtoks), np.stack(ref_toks)
            )
        finally:
            mesh_mod.finalize_distributed()

    @pytest.mark.slow
    def test_multi_paged_matches_chained_single(self, ctx4):
        """Paged multi-step: pool reads via the page table, all NS new
        rows landed by one scatter (append_n) — tokens and pool match
        chained single-step paged decode, crossing a page boundary."""
        from triton_distributed_tpu.models.paged_kv_cache import (
            as_dense,
            init_paged_cache,
            write_prefill,
        )

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        B, NS, page = 2, 4, 16

        # Context via the dense golden path, mirrored into pages.
        cache = model.new_cache(B, max_length=64)
        step_gold = model.decode_fn("xla")
        for toks in ([3, 5], [7, 11], [13, 17]):
            _, cache = step_gold(
                model.params, jnp.asarray(toks, jnp.int32), cache
            )
        # Push row 0 near a page boundary: positions 14..17 span pages.
        for toks in ([2, 4], [6, 8], [10, 12], [14, 1],
                     [9, 3], [5, 7], [11, 2], [8, 6],
                     [4, 9], [1, 5], [3, 8]):
            _, cache = step_gold(
                model.params, jnp.asarray(toks, jnp.int32), cache
            )
        paged, _pool = init_paged_cache(
            model.cfg, B, ctx4, max_length=64, page_size=page
        )
        for b in range(B):
            row = jax.tree.map(
                lambda x: x[:, b:b + 1], {"k": cache.k, "v": cache.v}
            )
            paged = write_prefill(
                paged, b, row["k"], row["v"], int(cache.kv_len[b])
            )

        mega = MegaQwen3(model)
        tok0 = jnp.asarray([19, 23], jnp.int32)

        # Reference: chained single-step paged mega decode.
        p_ref = jax.tree.map(jnp.copy, paged)
        t = tok0
        ref_toks = []
        for _ in range(NS):
            lg, p_ref = mega.decode_step(t, p_ref)
            t = jnp.argmax(lg, -1).astype(jnp.int32)
            ref_toks.append(np.asarray(t))

        s_max = int(paged.page_table.shape[1]) * page
        fn = mega.decode_multi_fn(B, s_max, NS, page=page)
        mtoks, _, p_out = fn(
            model.params, tok0, jax.tree.map(jnp.copy, paged)
        )
        np.testing.assert_array_equal(np.asarray(mtoks), np.stack(ref_toks))
        k_ref, v_ref = as_dense(p_ref)
        k_out, v_out = as_dense(p_out)
        np.testing.assert_allclose(
            np.asarray(k_out), np.asarray(k_ref), rtol=2e-3, atol=2e-3
        )
        np.testing.assert_allclose(
            np.asarray(v_out), np.asarray(v_ref), rtol=2e-3, atol=2e-3
        )
        np.testing.assert_array_equal(
            np.asarray(p_out.kv_len), np.asarray(p_ref.kv_len)
        )


class TestMegaServeFastPath:
    """PR 7: the megakernel composes with the production serving
    configuration — int8 paged pool read in-kernel through per-page
    scales, per-slot Gumbel sampling inside the NS launch, and split
    send-early/wait-late TP allreduces (docs/megakernel.md "Serving
    fast path")."""

    @staticmethod
    def _warm_pools(model, ctx, B=2, page=16, s_max=64):
        """Dense-golden context mirrored into paged pools (one int8,
        one full-width), plus the warmed dense cache."""
        from triton_distributed_tpu.models.paged_kv_cache import (
            init_paged_cache,
            write_prefill,
        )

        cache = model.new_cache(B, max_length=s_max)
        step_gold = model.decode_fn("xla")
        for toks in ([3, 5], [7, 11], [13, 17]):
            _, cache = step_gold(
                model.params, jnp.asarray(toks, jnp.int32), cache
            )

        def mk(kv_dtype):
            paged, _pool = init_paged_cache(
                model.cfg, B, ctx, max_length=s_max, page_size=page,
                kv_dtype=kv_dtype,
            )
            for b in range(B):
                row = jax.tree.map(
                    lambda x: x[:, b:b + 1], {"k": cache.k, "v": cache.v}
                )
                paged = write_prefill(
                    paged, b, row["k"], row["v"], int(cache.kv_len[b])
                )
            return paged

        return cache, mk

    @pytest.mark.slow
    def test_quant_paged_single_step_bit_parity(self, ctx4):
        """Greedy mega(int8) vs the unfused int8 paged xla decode,
        step-for-step: the in-kernel per-page dequant must produce the
        SAME token chain (both paths append through the one
        quantized_row_scatter protocol, so pools track within one code
        unit)."""
        from triton_distributed_tpu.models.paged_kv_cache import as_dense

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        _, mk = self._warm_pools(model, ctx4)
        q_mega, q_xla = mk("int8"), mk("int8")
        mega = MegaQwen3(model)
        tm = tx = jnp.asarray([19, 23], jnp.int32)
        for _ in range(6):
            lg_m, q_mega = mega.decode_step(tm, q_mega)
            lg_x, q_xla = model.decode_step(tx, q_xla, "xla")
            tm = jnp.argmax(lg_m, -1).astype(jnp.int32)
            tx = jnp.argmax(lg_x, -1).astype(jnp.int32)
            np.testing.assert_array_equal(np.asarray(tm), np.asarray(tx))
        km, _ = as_dense(q_mega)
        kx, _ = as_dense(q_xla)
        # One int8 code unit (amax/127) of slack: rows computed by
        # different kernels may round to adjacent codes.
        np.testing.assert_allclose(
            np.asarray(km), np.asarray(kx), atol=0.06
        )

    @pytest.mark.slow
    def test_quant_paged_multi_matches_chained_single(self, ctx4):
        """NS-step launch over the int8 pool vs NS chained single-step
        mega(int8) decodes: token-exact, pools within one quantization
        step. (Bit-identity of the pools is NOT expected here: the
        launch attends its own rows at full precision through the band
        while the chained steps re-read them quantized, so the K/V
        rows differ in low bits and may round to adjacent codes — the
        scale grow/requant EVENT ORDER itself is proven bit-exact by
        tests/test_kv_quant.py::test_append_n_sequential_scale_protocol
        over identical rows.)"""
        from triton_distributed_tpu.models.paged_kv_cache import as_dense

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        _, mk = self._warm_pools(model, ctx4)
        NS, page = 4, 16
        q_ref, q_multi = mk("int8"), mk("int8")
        mega = MegaQwen3(model)
        tok0 = jnp.asarray([19, 23], jnp.int32)
        t, ref_toks = tok0, []
        for _ in range(NS):
            lg, q_ref = mega.decode_step(t, q_ref)
            t = jnp.argmax(lg, -1).astype(jnp.int32)
            ref_toks.append(np.asarray(t))
        fn = mega.decode_multi_fn(
            2, 64, NS, page=page, kv_quant=True,
            num_pages=int(q_multi.k_pages.shape[1]),
        )
        mtoks, _, q_out = fn(model.params, tok0, q_multi)
        np.testing.assert_array_equal(
            np.asarray(mtoks), np.stack(ref_toks)
        )
        km, _ = as_dense(q_out)
        kr, _ = as_dense(q_ref)
        np.testing.assert_allclose(
            np.asarray(km), np.asarray(kr), atol=0.06
        )
        np.testing.assert_array_equal(
            np.asarray(q_out.kv_len), np.asarray(q_ref.kv_len)
        )

    @pytest.mark.slow
    @pytest.mark.parametrize("kv_dtype", [None, "int8"])
    def test_sampled_paged_multi(self, ctx4, kv_dtype):
        """Sampled multi-step over the PAGED pool (int8 included): the
        in-kernel argmax over logits + noise must match the host
        chaining tokens exactly — Gumbel-max temperature sampling on
        the serving cache layout."""
        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        _, mk = self._warm_pools(model, ctx4)
        NS, B, page = 3, 2, 16
        V = model.cfg.vocab_size
        v_pad = model.params.lm_head.shape[1]
        noise = 0.7 * jax.random.gumbel(
            jax.random.key(7), (NS, B, v_pad), jnp.float32
        )
        p_ref, p_s = mk(kv_dtype), mk(kv_dtype)
        mega = MegaQwen3(model)
        t, ref_toks = jnp.asarray([19, 23], jnp.int32), []
        for i in range(NS):
            lg, p_ref = mega.decode_step(t, p_ref)
            t = jnp.argmax(lg + noise[i, :, :V], -1).astype(jnp.int32)
            ref_toks.append(np.asarray(t))
        fn = mega.decode_multi_fn(
            B, 64, NS, sampled=True, page=page,
            kv_quant=kv_dtype is not None,
            num_pages=int(p_s.k_pages.shape[1]),
        )
        stoks, _, _ = fn(
            model.params, jnp.asarray([19, 23], jnp.int32), p_s, noise
        )
        np.testing.assert_array_equal(
            np.asarray(stoks), np.stack(ref_toks)
        )

    def test_overlap_ar_parity(self, ctx4):
        """Split AR_SEND/AR_WAIT allreduces (+ fused norms + cross-task
        prefetch — the serving default config) must match the golden
        decode step exactly: the overlap moves WHEN the puts fly and
        the reduction waits, never the math."""
        from triton_distributed_tpu.megakernel.code_generator import (
            MegaConfig,
        )

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        cache = model.new_cache(1, max_length=64)
        step_gold = model.decode_fn("xla")
        for t in (3, 5):
            _, cache = step_gold(
                model.params, jnp.asarray([t], jnp.int32), cache
            )
        tok = jnp.asarray([7], jnp.int32)
        logits_gold, _ = step_gold(
            model.params, tok, jax.tree.map(jnp.copy, cache)
        )
        ov = MegaQwen3(model, cfg=MegaConfig(
            fuse_norms=True, cross_prefetch=True, overlap_ar=True
        ))
        logits_ov, _ = ov.decode_step(tok, jax.tree.map(jnp.copy, cache))
        np.testing.assert_allclose(
            np.asarray(logits_ov), np.asarray(logits_gold),
            rtol=2e-3, atol=2e-3,
        )

    @pytest.mark.slow
    def test_overlap_ar_multi_step(self, ctx4):
        """Multi-step launches under overlap_ar: the split exchange's
        workspace/semaphore reuse must stay race-free across the NS
        in-launch steps AND the LM head's cross-rank argmax exchange —
        token-exact vs the chained overlap_ar single-step."""
        from triton_distributed_tpu.megakernel.code_generator import (
            MegaConfig,
        )

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        _, mk = self._warm_pools(model, ctx4)
        NS = 3
        ov = MegaQwen3(model, cfg=MegaConfig(
            fuse_norms=True, cross_prefetch=True, overlap_ar=True
        ))
        o_ref, o_m = mk(None), mk(None)
        t, ref_toks = jnp.asarray([19, 23], jnp.int32), []
        for _ in range(NS):
            lg, o_ref = ov.decode_step(t, o_ref)
            t = jnp.argmax(lg, -1).astype(jnp.int32)
            ref_toks.append(np.asarray(t))
        fn = ov.decode_multi_fn(2, 64, NS, page=16)
        otoks, _, _ = fn(
            model.params, jnp.asarray([19, 23], jnp.int32), o_m
        )
        np.testing.assert_array_equal(
            np.asarray(otoks), np.stack(ref_toks)
        )

    def test_overlap_ar_task_graph(self, ctx4):
        """overlap_ar splits every allreduce into AR_SEND + AR_WAIT
        (one extra task per exchange), adjacently scheduled."""
        from triton_distributed_tpu.megakernel.code_generator import (
            MegaConfig,
        )

        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        base = MegaQwen3(model)
        split = MegaQwen3(model, cfg=MegaConfig(overlap_ar=True))
        n_base = len(base._built(1, 64)[0].order)
        n_split = len(split._built(1, 64)[0].order)
        L = model.cfg.num_layers
        assert n_split - n_base == 2 * L  # 2 exchanges per layer
        types = [t.task_type for t in split._built(1, 64)[0].order]
        assert TaskType.ALLREDUCE not in types
        assert types.count(TaskType.AR_SEND) == 2 * L
        assert types.count(TaskType.AR_WAIT) == 2 * L
        # Every AR_SEND is immediately followed by its AR_WAIT (the
        # sequential-chain deps pin the pair together).
        for i, tt in enumerate(types):
            if tt == TaskType.AR_SEND:
                assert types[i + 1] == TaskType.AR_WAIT


class TestMultiStepWide:
    """NS=16 launch width (the ladder's TDT_BENCH_NS=16 rung): the SMEM
    token table, in-launch KV band, and feedback chain must hold at 2x
    the default width."""

    @pytest.mark.slow
    def test_multi_ns16_matches_chained_single(self, ctx1):
        model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
        B, NS = 1, 16
        cache = model.new_cache(B, max_length=64)
        step_gold = model.decode_fn("xla")
        _, cache = step_gold(model.params, jnp.asarray([3], jnp.int32), cache)

        mega = MegaQwen3(model)
        s_max = int(cache.k.shape[3])
        tok0 = jnp.asarray([19], jnp.int32)

        step = mega.decode_fn(B, s_max)
        t, c = tok0, jax.tree.map(jnp.copy, cache)
        ref_toks = []
        for _ in range(NS):
            lg, c = step(model.params, t, c)
            t = jnp.argmax(lg, -1).astype(jnp.int32)
            ref_toks.append(np.asarray(t))

        multi = mega.decode_multi_fn(B, s_max, NS)
        mtoks, _ml, _mc = multi(
            model.params, tok0, jax.tree.map(jnp.copy, cache)
        )
        np.testing.assert_array_equal(np.asarray(mtoks), np.stack(ref_toks))
