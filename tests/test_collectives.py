"""Collective correctness vs numpy goldens on the simulated mesh.

Parity: reference ``test_all_gather.py``, ``test_reduce_scatter.py``,
``test_allreduce.py``, ``test_all_to_all.py`` — golden there is
torch/NCCL; here it is numpy on the host.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.ops import (
    AllGatherMethod,
    AllReduceMethod,
    ReduceScatterMethod,
    all_gather_op,
    all_reduce_op,
    all_to_all_op,
    reduce_scatter_op,
)


@pytest.mark.parametrize(
    "method",
    [
        AllGatherMethod.XLA,
        AllGatherMethod.PALLAS_RING,
        AllGatherMethod.PALLAS_BIDIR_RING,
        AllGatherMethod.PALLAS_FULL_MESH,
        AllGatherMethod.PALLAS_PULL,
    ],
)
def test_all_gather(ctx4, rng, method):
    x = jnp.asarray(rng.standard_normal((4 * 8, 128), dtype=np.float32))
    out = all_gather_op(x, "tp", method, ctx4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


@pytest.mark.parametrize("window", [1, 2, 3])
def test_all_gather_pull_windows(ctx4, rng, window):
    """Pull (receiver-driven) gather at every pacing window, incl. the
    fully-serialized window=1 — exercises the request/serve_get
    rendezvous and its deadlock-freedom argument at each depth."""
    x = jnp.asarray(rng.standard_normal((4 * 8, 128), dtype=np.float32))
    out = all_gather_op(
        x, "tp", AllGatherMethod.PALLAS_PULL, ctx4, pull_window=window
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


@pytest.mark.parametrize(
    "method",
    [
        ReduceScatterMethod.XLA,
        ReduceScatterMethod.ONE_SHOT,
        ReduceScatterMethod.PALLAS_RING,
        ReduceScatterMethod.PALLAS_BIDIR_RING,
        ReduceScatterMethod.PALLAS_RING_HBM,
    ],
)
def test_reduce_scatter(ctx4, rng, method):
    n = 4
    x = jnp.asarray(rng.standard_normal((n, n * 8, 128), dtype=np.float32))
    out = reduce_scatter_op(x, "tp", method, ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x).sum(0), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize(
    "method",
    [AllReduceMethod.XLA, AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT,
     AllReduceMethod.DOUBLING],
)
def test_all_reduce(ctx4, rng, method):
    n = 4
    x = jnp.asarray(rng.standard_normal((n, 16, 128), dtype=np.float32))
    out = all_reduce_op(x, "tp", method, ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x).sum(0), rtol=1e-5, atol=1e-5
    )


def test_all_reduce_auto_dispatch():
    from triton_distributed_tpu.ops import get_auto_allreduce_method

    assert get_auto_allreduce_method(1024, 8) == AllReduceMethod.ONE_SHOT
    # mid-size band on a power-of-two axis: log-depth butterfly
    assert get_auto_allreduce_method(1 << 19, 8) == AllReduceMethod.DOUBLING
    assert get_auto_allreduce_method(1 << 19, 6) == AllReduceMethod.TWO_SHOT
    assert get_auto_allreduce_method(1 << 21, 8) == AllReduceMethod.TWO_SHOT
    # no XLA fallback on size: beyond the VMEM ceiling the TWO_SHOT RS
    # leg switches to the HBM-slot ring internally
    assert get_auto_allreduce_method(1 << 24, 8) == AllReduceMethod.TWO_SHOT
    assert get_auto_allreduce_method(1 << 24, 2) == AllReduceMethod.TWO_SHOT


@pytest.mark.parametrize("method", ["xla", "pallas"])
def test_all_to_all(ctx4, rng, method):
    n = 4
    x = jnp.asarray(rng.standard_normal((n, n * 8, 128), dtype=np.float32))
    out = all_to_all_op(x, "tp", method, ctx4)
    xs = np.asarray(x).reshape(n, n, 8, 128)
    expect = np.transpose(xs, (1, 0, 2, 3)).reshape(n, n * 8, 128)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-6)


def test_all_gather_bf16(ctx4, rng):
    x = jnp.asarray(rng.standard_normal((4 * 16, 256), dtype=np.float32)).astype(
        jnp.bfloat16
    )
    out = all_gather_op(x, "tp", AllGatherMethod.PALLAS_BIDIR_RING, ctx4)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_collectives_respect_dp_axis(ctx2x4, rng):
    """Ring on tp must not leak across dp replicas (MESH addressing)."""
    x = jnp.asarray(rng.standard_normal((2 * 4 * 8, 128), dtype=np.float32))
    from jax.sharding import PartitionSpec as P
    from triton_distributed_tpu.ops.collectives.all_gather import all_gather

    def body(xi):
        return all_gather(xi, "tp", AllGatherMethod.PALLAS_RING, ctx2x4)

    f = ctx2x4.shard_map(
        body, in_specs=P(("dp", "tp"), None), out_specs=P("dp", None)
    )
    out = np.asarray(f(x))  # [2 * 4*8, 128]: per-dp gathered rows
    xs = np.asarray(x).reshape(2, 32, 128)
    np.testing.assert_allclose(out.reshape(2, 32, 128), xs, rtol=1e-6)


class TestHierarchical:
    """Two-level ICI/DCN collectives (parity: reference 2D/NUMA-aware
    variants + reduce_scatter_multi_node; dp stands in for the DCN axis
    on the simulated mesh)."""

    def test_all_gather_2d(self, ctx2x4, rng):
        from triton_distributed_tpu.ops.collectives.hierarchical import (
            all_gather_2d_op,
        )

        x = jnp.asarray(rng.standard_normal((8 * 4, 128), dtype=np.float32))
        out = all_gather_2d_op(x, inner_axis="tp", outer_axis="dp", ctx=ctx2x4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)

    def test_all_reduce_2level(self, ctx2x4, rng):
        from triton_distributed_tpu.ops.collectives.hierarchical import (
            all_reduce_2level_op,
        )

        x = jnp.asarray(rng.standard_normal((8, 16, 128), dtype=np.float32))
        out = all_reduce_2level_op(x, inner_axis="tp", outer_axis="dp", ctx=ctx2x4)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(x).sum(0), rtol=1e-4, atol=1e-4
        )

    def test_reduce_scatter_2d(self, ctx2x4, rng):
        from jax.sharding import PartitionSpec as P
        from triton_distributed_tpu.ops.collectives.hierarchical import (
            reduce_scatter_2d,
        )

        n_in, n_out, m = 4, 2, 8
        M = n_in * n_out * m
        x = jnp.asarray(
            rng.standard_normal((n_in * n_out, M, 128), dtype=np.float32)
        )

        def body(xi):
            return reduce_scatter_2d(
                xi[0], inner_axis="tp", outer_axis="dp", ctx=ctx2x4
            )

        f = ctx2x4.shard_map(
            body,
            in_specs=P(("dp", "tp"), None, None),
            # chunks come back inner-major: chunk id = tp * n_dp + dp
            out_specs=P(("tp", "dp"), None),
        )
        out = np.asarray(f(x))
        np.testing.assert_allclose(
            out, np.asarray(x).sum(0), rtol=1e-4, atol=1e-4
        )


class TestLowLatencyAllGather:
    """LL (barrier-free on TPU) allgather — reference
    low_latency_allgather.py parity; interpret mode runs the documented
    entry-barrier shim."""

    def test_matches_identity(self, ctx4, rng):
        from triton_distributed_tpu.ops import ll_all_gather_op

        x = jnp.asarray(rng.standard_normal((4 * 8, 128)), np.float32)
        out = ll_all_gather_op(x, steps=1, axis="tp", ctx=ctx4)
        assert not np.isnan(np.asarray(out)).any()
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))

    def test_phase_rotation(self, ctx4, rng):
        """Three chained calls exercise both workspace slots + reuse."""
        from triton_distributed_tpu.ops import ll_all_gather_op

        x = jnp.asarray(rng.standard_normal((4 * 8, 128)), np.float32)
        out = ll_all_gather_op(x, steps=3, axis="tp", ctx=ctx4)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x))


@pytest.mark.parametrize(
    "method", ["xla", "one_shot"]
)
@pytest.mark.parametrize("root", [0, 2])
def test_broadcast(ctx4, rng, method, root):
    from triton_distributed_tpu.ops import BroadcastMethod, broadcast_op

    x = jnp.asarray(rng.standard_normal((4, 16, 128), dtype=np.float32))
    out = broadcast_op(x, "tp", root, BroadcastMethod(method), ctx4)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x)[root], rtol=1e-6
    )


def test_all_gather_torus_2d(ctx2x4, rng):
    """Fused 2D-torus gather (one kernel, both axes' links): rank-major
    result must equal a plain two-axis gather."""
    from jax.sharding import PartitionSpec as P
    from triton_distributed_tpu.ops.collectives.all_gather import (
        all_gather_torus_2d,
    )

    x = jnp.asarray(rng.standard_normal((8 * 8, 128), dtype=np.float32))

    def body(xi):
        return all_gather_torus_2d(xi, axes=("dp", "tp"), ctx=ctx2x4)

    f = ctx2x4.shard_map(
        body, in_specs=P(("dp", "tp"), None), out_specs=P(None, None)
    )
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(x), rtol=1e-6)


def test_reduce_scatter_bidir_8dev(ctx8, rng):
    """Dual counter-rotating RS rings at n=8 (both directions' slot and
    neighbor algebra exercised over more than one hop)."""
    n = 8
    x = jnp.asarray(rng.standard_normal((n, n * 4, 128), dtype=np.float32))
    out = reduce_scatter_op(
        x, "tp", ReduceScatterMethod.PALLAS_BIDIR_RING, ctx8
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(x).sum(0), rtol=1e-5, atol=1e-5
    )
