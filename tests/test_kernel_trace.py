"""Device task tracer (ISSUE 8): in-kernel timeline for the megakernel.

Coverage contract (ISSUE 8 acceptance):
- tracer OFF → untraced builds keep the PR 7 return arity and produce
  bit-identical outputs to traced builds' primary outputs;
- tracer ON → decoded ring is gap-free and dependency-order consistent
  with the scheduler (begin[consumer] >= end[producer] for every
  scoreboard edge) under interpret at tp=1 and tp=4;
- engine wiring: ContinuousEngine(kernel_trace=True) outputs match the
  untraced engine bit-exactly, launches land in metrics + the
  {"cmd": "kernel_trace"} verb, and request trace ids flow through
  admit events → mega:launch events → ring launch metadata;
- the merged chrome timeline carries host spans AND device task rows
  for the same trace id.
"""

import gzip
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from triton_distributed_tpu.megakernel import MegaQwen3, TaskType
from triton_distributed_tpu.megakernel.code_generator import MegaConfig
from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.obs import kernel_trace as kt


@pytest.fixture
def ctx1():
    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    yield ctx
    mesh_mod.finalize_distributed()


def _warm_cache(model, B=2, s_max=64, warm=((3, 5),)):
    cache = model.new_cache(B, max_length=s_max)
    step = model.decode_fn("xla")
    for toks in warm:
        _, cache = step(
            model.params, jnp.asarray(list(toks)[:B], jnp.int32), cache
        )
    return cache


class TestRingTp1:
    def test_multi_trace_bit_identity_and_ring(self, ctx1):
        """tp=1, NS=3: traced launch's tokens/logits/cache match the
        untraced build bit-exactly; the ring decodes gap-free, clock-
        monotonic, and dependency-consistent with the scheduled order;
        the untraced build keeps the PR 7 3-tuple contract."""
        model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
        B, NS = 2, 3
        cache = _warm_cache(model, B)
        mega = MegaQwen3(model)
        s_max = int(cache.k.shape[3])
        tok0 = jnp.asarray([19, 23], jnp.int32)

        f0 = mega.decode_multi_fn(B, s_max, NS)
        out0 = f0(model.params, tok0, jax.tree.map(jnp.copy, cache))
        assert len(out0) == 3  # PR 7 contract untouched with trace off
        # Untraced LAUNCH PARAMS bit-identical to the pre-tracer
        # layout: the task table's id column stays zero with trace
        # off (a tracer-only operand extension).
        from triton_distributed_tpu.megakernel.task import pack_table

        order0 = mega.multi_task_order(B, s_max, NS)
        tab_off = pack_table(order0)
        assert (tab_off[:, 4:] == 0).all()
        tab_on = pack_table(order0, trace=True)
        assert tab_on[:, 4].tolist() == [t.task_id for t in order0]
        np.testing.assert_array_equal(tab_off[:, :4], tab_on[:, :4])

        f1 = mega.decode_multi_fn(B, s_max, NS, trace=True)
        t1, l1, c1, ring = f1(
            model.params, tok0, jax.tree.map(jnp.copy, cache)
        )
        t0_, l0, c0 = out0
        np.testing.assert_array_equal(np.asarray(t0_), np.asarray(t1))
        np.testing.assert_array_equal(np.asarray(l0), np.asarray(l1))
        np.testing.assert_array_equal(np.asarray(c0.k), np.asarray(c1.k))
        np.testing.assert_array_equal(
            np.asarray(c0.kv_len), np.asarray(c1.kv_len)
        )

        ring = np.asarray(ring)
        order = mega.multi_task_order(B, s_max, NS, trace=True)
        assert ring.shape == (1, NS, len(order), 8)
        records = kt.decode_trace(ring)  # strict: raises on any gap
        assert len(records) == NS * len(order)
        problems = kt.validate_ring(records, order)
        assert problems == []
        # task_id stamping survives the schedule: ids in the ring are
        # exactly the builder's ids, not positions.
        assert ({r.task_id for r in records}
                == {t.task_id for t in order})
        # The fused single-rank exchange stamps its comm phase.
        ar = [r for r in records
              if r.opcode == int(TaskType.ALLREDUCE)]
        assert ar and all(r.begin <= r.mid <= r.end for r in ar)

    def test_single_step_trace_build(self, ctx1):
        """``build(trace=True)``: the single-step path returns
        (logits, cache, ring [tp, 1, T, 8]) and the ring decodes
        cleanly; trace=False keeps the 2-tuple step."""
        model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
        cache = _warm_cache(model, B=1)
        mega = MegaQwen3(model)
        tok = jnp.asarray([7], jnp.int32)
        compiled, step, _ = mega.build(1, 64, trace=True)
        logits, c2, ring = step(
            model.params, tok, jax.tree.map(jnp.copy, cache)
        )
        ring = np.asarray(ring)
        assert ring.shape == (1, 1, compiled.num_tasks, 8)
        records = kt.decode_trace(ring)
        assert kt.validate_ring(records, compiled.order) == []
        # Untraced contract unchanged.
        _, step0, _ = mega.build(1, 64)
        out = step0(model.params, tok, cache)
        assert len(out) == 2
        np.testing.assert_array_equal(
            np.asarray(out[0]), np.asarray(logits)
        )


class TestRingTp4:
    def test_overlap_ar_ring_and_exposure(self, ctx4):
        """tp=4 serving config (fuse_norms+cross_prefetch+overlap_ar):
        every rank's ring is gap-free and dependency-consistent, every
        AR_SEND/AR_WAIT pair stamps its phase marks, and the measured
        overlap report opens one window per exchange with nonzero
        hidden time (the tile-0 prefetch the wait fires before
        blocking)."""
        model = AutoLLM.from_pretrained("tiny", ctx=ctx4)
        B, NS = 1, 2
        cache = _warm_cache(model, B, warm=((3,), (5,)))
        mega = MegaQwen3(model, cfg=MegaConfig(
            fuse_norms=True, cross_prefetch=True, overlap_ar=True
        ))
        s_max = int(cache.k.shape[3])
        fn = mega.decode_multi_fn(B, s_max, NS, trace=True)
        _toks, _lg, _c, ring = fn(
            model.params, jnp.asarray([19], jnp.int32), cache
        )
        ring = np.asarray(ring)
        assert ring.shape[0] == 4  # one ring per rank
        order = mega.multi_task_order(B, s_max, NS, trace=True)
        records = kt.decode_trace(ring)
        assert kt.validate_ring(records, order) == []
        L = model.cfg.num_layers
        sends = [r for r in records if r.opcode == int(TaskType.AR_SEND)]
        waits = [r for r in records if r.opcode == int(TaskType.AR_WAIT)]
        # 2 exchanges per layer × NS steps × 4 ranks.
        assert len(sends) == len(waits) == 2 * L * NS * 4
        assert all(r.begin <= r.mid <= r.end for r in sends + waits)
        rep = kt.overlap_report(records)
        assert rep["windows"] == 2 * L * NS * 4
        # The wait's pre-block phase (tile-0 fire) is measured hidden
        # time inside every window.
        assert rep["hidden_ticks"] > 0
        assert rep["comm_ticks"] >= rep["exposed_ticks"]
        assert 0.0 < rep["hidden_fraction"] <= 1.0
        # The vectorized inline path (what the serving loop pays per
        # launch) must agree exactly with the record-wise reference.
        assert kt._overlap_report_array(ring) == rep


class TestDecoderPure:
    """Host-side decoder invariants on synthetic rings (no kernels)."""

    @staticmethod
    def _row(task_id, opcode, begin, end, mid=0, layer=0, slot=0, flag=1):
        return [task_id, opcode, layer, slot, begin, end, mid, flag]

    def test_gap_raises_strict_and_skips_unstrict(self):
        ring = np.asarray([[[
            self._row(0, 0, 1, 2),
            self._row(1, 1, 3, 4, flag=0),  # unwritten
        ]]], np.int32)
        with pytest.raises(kt.TraceError, match="gaps"):
            kt.decode_trace(ring)
        recs = kt.decode_trace(ring, strict=False)
        assert [r.task_id for r in recs] == [0]

    def test_validate_flags_order_violations(self):
        from triton_distributed_tpu.megakernel.task import (
            Task,
            TaskDependency,
        )

        order = [
            Task(task_id=0, task_type=TaskType.EMBED),
            Task(task_id=1, task_type=TaskType.NORM,
                 deps=(TaskDependency(0),)),
        ]
        # Consumer begins BEFORE its producer ended.
        ring = np.asarray([[[
            self._row(0, int(TaskType.EMBED), 5, 8),
            self._row(1, int(TaskType.NORM), 9, 12),
        ]]], np.int32)
        good = kt.decode_trace(ring)
        assert kt.validate_ring(good, order) == []
        bad_ring = np.asarray([[[
            self._row(0, int(TaskType.EMBED), 5, 8),
            self._row(1, int(TaskType.NORM), 7, 12),
        ]]], np.int32)
        bad = kt.decode_trace(bad_ring)
        probs = kt.validate_ring(bad, order)
        assert probs and any("before" in p for p in probs)
        # Degenerate interval.
        deg = kt.decode_trace(np.asarray(
            [[[self._row(0, 0, 5, 5)]]], np.int32
        ))
        assert any(">=" in p for p in kt.validate_ring(deg))

    def test_overlap_report_exact_on_synthetic_pair(self):
        # AR_SEND [10, 12] (puts in flight at 11), two compute tasks,
        # AR_WAIT [20, 26] (tile-0 fired at 22 → blocked [22, 26]).
        ring = np.asarray([[[
            self._row(0, int(TaskType.AR_SEND), 10, 12, mid=11),
            self._row(1, int(TaskType.QKV_PROJ), 13, 17),
            self._row(2, int(TaskType.ATTN), 17, 20),
            self._row(3, int(TaskType.AR_WAIT), 20, 26, mid=22),
        ]]], np.int32)
        rep = kt.overlap_report(kt.decode_trace(ring))
        assert rep["windows"] == 1
        assert rep["comm_ticks"] == 26 - 11
        # hidden = wait pre-block (2) + qkv (4) + attn (3) = 9
        assert rep["hidden_ticks"] == 9
        assert rep["exposed_ticks"] == 26 - 22
        assert rep["hidden_fraction"] == pytest.approx(9 / 15)

    def test_merge_with_host_profile_one_file(self, tmp_path):
        """Host spans + device task rows land in ONE merged gzip, the
        device rows inside the rank's pid namespace and tagged with the
        launch's request trace ids."""
        from triton_distributed_tpu.runtime.profiling import _PID_STRIDE

        root = tmp_path / "prof" / "run" / "rank0"
        sess = root / "plugins" / "profile" / "s1"
        sess.mkdir(parents=True)
        host = {"traceEvents": [
            {"ph": "M", "name": "process_name", "pid": 1,
             "args": {"name": "host"}},
            {"ph": "X", "name": "prefix_cache:admit", "pid": 1,
             "tid": 1, "ts": 0, "dur": 5,
             "args": {"trace_id": "req-42"}},
        ]}
        with gzip.open(str(sess / "h.trace.json.gz"), "wt") as f:
            json.dump(host, f)
        records = kt.decode_trace(np.asarray([[[
            self._row(0, int(TaskType.EMBED), 1, 2),
            self._row(1, int(TaskType.LM_HEAD), 3, 4),
        ]]], np.int32))
        launch = kt.KernelTraceLaunch(
            records=records, wall_s=0.5, t0=1.0,
            trace_ids={0: "req-42"}, nsteps=1, launch=1,
        )
        out = kt.merge_with_host_profile(
            "run", str(tmp_path / "prof"), [launch]
        )
        with gzip.open(out, "rt") as f:
            merged = json.load(f)
        evs = merged["traceEvents"]
        host_rows = [e for e in evs
                     if e.get("name") == "prefix_cache:admit"]
        dev_rows = [e for e in evs if e.get("name") in ("EMBED", "LM_HEAD")]
        assert len(host_rows) == 1 and len(dev_rows) == 2
        # Device rows live inside rank 0's namespace at the device pid.
        assert {e["pid"] for e in dev_rows} == {kt.DEVICE_TASK_PID}
        assert all(e["pid"] < _PID_STRIDE for e in dev_rows)
        # The SAME trace id on the host span and the device rows.
        assert host_rows[0]["args"]["trace_id"] == "req-42"
        assert all("req-42" in e["args"]["trace_ids"] for e in dev_rows)
        names = {e["args"]["name"] for e in evs
                 if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
        assert "rank0: device tasks" in names
        # No host traces on disk → a device-only timeline still lands.
        out2 = kt.merge_with_host_profile(
            "empty", str(tmp_path / "prof"), [launch]
        )
        with gzip.open(out2, "rt") as f:
            only_dev = json.load(f)
        assert all(e.get("name") != "prefix_cache:admit"
                   for e in only_dev["traceEvents"])

    def test_summary_and_observe_launch(self, fresh_telemetry):
        from triton_distributed_tpu.obs import metrics as obs_metrics

        records = kt.decode_trace(np.asarray([[[
            self._row(0, int(TaskType.EMBED), 1, 3),
            self._row(1, int(TaskType.LM_HEAD), 3, 9),
        ]]], np.int32))
        launch = kt.KernelTraceLaunch(
            records=records, wall_s=0.8, t0=0.0,
            trace_ids={0: "a", 1: "b"}, nsteps=1, launch=7,
        )
        s = launch.summary()
        assert s["ticks_by_opcode"] == {"EMBED": 2, "LM_HEAD": 6}
        assert s["trace_ids"] == {0: "a", 1: "b"}
        kt.observe_launch(launch)
        reg = obs_metrics.default_registry()
        hist = reg.get("tdt_mega_task_seconds")
        assert hist.count(opcode="LM_HEAD") == 1
        # ticks scale to the measured wall: 6/8 of 0.8 s.
        assert hist.quantile(0.5, opcode="LM_HEAD") == pytest.approx(
            0.6, rel=0.5
        )


class TestEngineAndServer:
    def test_continuous_engine_trace_and_verbs(self, ctx1,
                                               fresh_telemetry):
        """ONE engine compile covers the serving acceptance: traced
        engine output == untraced engine output bit-exactly; launches
        decoded into metrics/summary with request trace ids; the
        kernel_trace and kind-filtered events verbs answer through the
        wire; trace_ids payload key tags requests end to end."""
        from triton_distributed_tpu.models.continuous import (
            ContinuousEngine,
        )
        from triton_distributed_tpu.obs import events as obs_events
        from triton_distributed_tpu.obs import metrics as obs_metrics
        from triton_distributed_tpu.serving.server import (
            ModelServer,
            request,
        )

        model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
        reqs = [(list(range(1, 9)), 12), (list(range(3, 15)), 10)]
        e0 = ContinuousEngine(
            model, max_batch=2, max_length=64, page_size=16, mode="mega",
        )
        out0 = e0.run(reqs, results=True)
        e1 = ContinuousEngine(
            model, max_batch=2, max_length=64, page_size=16, mode="mega",
            kernel_trace=True,
        )
        out1 = e1.run(reqs, results=True)
        for a, b in zip(out0, out1):
            assert a.status == b.status == "ok"
            np.testing.assert_array_equal(a.tokens, b.tokens)

        # Launch ledger + registry.
        assert e1.stats["mega_trace_launches"] >= 1
        assert (e1.stats["mega_trace_launches"]
                == e1.stats["mega_launches"])
        summary = e1.kernel_trace_summary()
        assert summary["enabled"] and summary["launches"] >= 1
        last = summary["recent"][-1]
        assert last["records"] > 0 and last["ticks_by_opcode"]
        # Trace ids attached to the launch metadata…
        assert last["trace_ids"]
        reg = obs_metrics.default_registry()
        assert reg.get("tdt_mega_task_seconds").count(
            opcode="ATTN"
        ) > 0
        # …and on admit + mega:launch events (server→device thread).
        evts, _ = obs_events.default_ring().tail(0, kind="admit")
        admit_ids = {e.fields.get("trace_id") for e in evts}
        launch_evts, _ = obs_events.default_ring().tail(
            0, kind="mega:launch"
        )
        assert launch_evts
        launched_ids = set()
        for e in launch_evts:
            launched_ids.update(
                x for x in e.fields.get("trace_ids", "").split(",") if x
            )
        assert launched_ids and launched_ids <= admit_ids

        # Wire: kernel_trace verb, kind-filtered events, trace_ids key.
        server = ModelServer(e1).start()
        try:
            r = request(server.host, server.port, {"cmd": "kernel_trace"})
            assert r["kernel_trace"]["enabled"]
            assert r["kernel_trace"]["launches"] >= 1
            r2 = request(server.host, server.port, {
                "requests": [list(range(1, 9))], "gen_lens": [9],
                "trace_ids": ["wire-req-1"],
            })
            assert [x["status"] for x in r2["results"]] == ["ok"]
            ev = request(server.host, server.port,
                         {"cmd": "events", "kind": "admit"})
            assert ev["events"]
            assert all(e["kind"] == "admit" for e in ev["events"])
            assert any(
                e["fields"].get("trace_id") == "wire-req-1"
                for e in ev["events"]
            )
            # kind with no matches: cursor still advances (progress).
            none = request(server.host, server.port,
                           {"cmd": "events", "kind": "no_such_kind"})
            assert none["events"] == []
            assert none["next_since"] >= ev["next_since"] - 1
            with pytest.raises(RuntimeError, match="kind must be a"):
                request(server.host, server.port,
                        {"cmd": "events", "kind": 7})
            st = request(server.host, server.port, {"cmd": "stats"})
            assert st["stats"]["server"]["engine"]["kernel_trace"] is True
        finally:
            request(server.host, server.port, {"cmd": "shutdown"})
            server.shutdown()

    def test_fixed_batch_engine_trace(self, ctx1, fresh_telemetry):
        """``Engine(mode="mega", kernel_trace=True)``: the serve()
        multi-step launches record rings too — deterministic across
        serves, launches decoded into the summary/metrics. (Traced-vs-
        untraced bit-identity is pinned at kernel level in TestRingTp1
        and at engine level for ContinuousEngine above — a second mega
        Engine build here would only re-prove it at tier-1 wall cost.)"""
        from triton_distributed_tpu.models.engine import Engine
        from triton_distributed_tpu.obs import metrics as obs_metrics

        model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
        ids = [list(range(1, 9))]
        e1 = Engine(model, mode="mega", kernel_trace=True)
        out1 = e1.serve(ids, 9, max_length=64)
        out2 = e1.serve(ids, 9, max_length=64)
        np.testing.assert_array_equal(out1, out2)
        assert e1.last_stats["mega_trace_launches"] >= 2
        s = e1.kernel_trace_summary()
        assert s["enabled"] and s["launches"] >= 1
        assert s["recent"][-1]["ticks_by_opcode"]
        assert kt.validate_ring(
            e1.kernel_trace_launches()[-1].get_records()
        ) == []
        reg = obs_metrics.default_registry()
        assert reg.get("tdt_mega_task_seconds").count(opcode="ATTN") > 0

    def test_sync_tables_never_aliases_host_arrays(self, ctx1):
        """Regression (found by the tracer's wider dispatch→fetch
        window): ``jnp.asarray`` on CPU may zero-copy an aligned numpy
        array, so the engine's device page_table/kv_len could ALIAS
        the live host arrays it keeps mutating — an async launch then
        raced host bookkeeping (run-to-run token flips). _sync_tables
        must hand the device its own storage: later in-place host
        mutations may never show through."""
        from triton_distributed_tpu.models.continuous import (
            ContinuousEngine,
        )

        model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
        eng = ContinuousEngine(
            model, max_batch=2, max_length=64, page_size=16, mode="mega",
        )
        eng._kv_len[:] = 0
        eng._table[:] = 0
        eng._sync_tables()
        before_kv = np.asarray(eng.cache.kv_len).copy()
        before_tab = np.asarray(eng.cache.page_table).copy()
        eng._kv_len += 7            # in-place host mutations...
        eng._table[:, 0] = 3
        np.testing.assert_array_equal(          # ...never reach the
            np.asarray(eng.cache.kv_len), before_kv)     # device copy
        np.testing.assert_array_equal(
            np.asarray(eng.cache.page_table), before_tab)

    def test_kernel_trace_requires_mega(self, ctx1):
        from triton_distributed_tpu.models.continuous import (
            ContinuousEngine,
        )
        from triton_distributed_tpu.models.engine import Engine

        model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
        with pytest.raises(ValueError, match="mode='mega'"):
            ContinuousEngine(model, mode="xla", kernel_trace=True)
        with pytest.raises(ValueError, match="mode='mega'"):
            Engine(model, mode="xla", kernel_trace=True)

    def test_kernel_trace_verb_refused_without_tracer(self, ctx1):
        """A server over an engine with no tracer surface answers the
        verb with a structured bad_request, not an internal error."""
        from triton_distributed_tpu.serving.server import ModelServer

        class NoTracer:
            last_stats = {}

        server = ModelServer(NoTracer())
        try:
            resp = server._dispatch_inner({"cmd": "kernel_trace"})
        finally:
            server._sock.close()
        assert resp["error"]["status"] == "bad_request"
        assert "tracer" in resp["error"]["reason"]


class TestGemmArRing:
    def test_trace_plumb_shapes_and_refusal(self, ctx4):
        """The standalone gemm_ar ONE_SHOT kernel carries the same
        ring format (abstract-eval only: the barrier-semaphore path
        cannot execute under this container's interpret — the ring is
        a hardware-path feature; its decoder is shared and tested on
        megakernel rings above)."""
        from triton_distributed_tpu.ops.overlap.gemm_ar import (
            GemmARConfig,
            GemmARMethod,
            gemm_ar_op,
        )

        a = jnp.zeros((16, 256), jnp.float32)
        b = jnp.zeros((256, 256), jnp.float32)
        sh = jax.eval_shape(
            lambda a_, b_: gemm_ar_op(
                a_, b_, "tp", GemmARMethod.ONE_SHOT,
                GemmARConfig(tile_n=128), ctx4, trace=True,
            ),
            a, b,
        )
        assert sh[0].shape == (16, 256)
        # [ranks, num_j + 1, phases, TRACE_INTS]
        assert sh[1].shape == (4, 3, 3, 8) and sh[1].dtype == jnp.int32
        with pytest.raises(ValueError, match="ONE_SHOT"):
            gemm_ar_op(a, b, "tp", GemmARMethod.AUTO, None, ctx4,
                       trace=True)

    def test_single_rank_trace_keeps_arity(self, ctx1):
        """n_ranks == 1 (nothing to overlap, no fused kernel): the
        traced call still returns (out, ring) — an all-unwritten ring
        that strict=False decodes to [] — instead of crashing the
        caller's unpack."""
        from triton_distributed_tpu.ops.overlap.gemm_ar import (
            GemmARConfig,
            GemmARMethod,
            gemm_ar_op,
        )

        a = jnp.ones((16, 128), jnp.float32)
        b = jnp.ones((128, 256), jnp.float32)
        out, ring = gemm_ar_op(
            a, b, "tp", GemmARMethod.ONE_SHOT,
            GemmARConfig(tile_n=128), ctx1, trace=True,
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(a @ b))
        assert kt.decode_trace(np.asarray(ring), strict=False) == []
