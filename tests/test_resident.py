"""Resident megakernel decode (ISSUE 19): host work ring, in-kernel
top-k/top-p, batch-bucket launches, device-side stop-token retire.

Coverage contract (ISSUE 19 acceptance):
- WorkRing semantics: publish-then-consume round protocol, monotonic
  doorbell, loud overflow (a dropped admit/retire item would
  desynchronize the device scheduler from the engine's slot state);
- ``validate_ring``'s doorbell-gap check: a RING_POLL record that
  observed a doorbell the host did not publish for that launch flags
  as a stale ring snapshot;
- the new ``tdt_mega_*`` ring/retire series pre-touch to 0 at engine
  construction (the PR 15 convention: a cold counter must READ 0 on
  the dashboard, not be missing), and
  ``tdt_mega_single_step_fallbacks_total`` scrapes 0 after a PURE
  SAMPLED mega run — the in-kernel filter replaced the fallback;
- both serving CLIs refuse --speculative × --mode mega with the
  ring-splice reason (the flag-name substring is pinned by
  test_tools.py; THIS file pins the new wording);
- device-side stop-token retire: a slot hitting eos mid-multi-step
  retires with no host round trip, its pages flow back through the
  normal teardown path (radix tree receives the chain, pool audit
  clean), and the co-batched survivor's tokens are bit-exact;
- batch-bucket launches emit bit-identical tokens to the full-width
  program; the resident pipeline's rings validate gap-free against
  their published doorbells;
- review hardening: ``consume`` stops at the publish snapshot and
  ``flush`` drains fallback rounds host-side (a persistently
  falling-back workload must not overflow the ring and wedge the
  engine), no-op filter knobs (top_k >= V, top_p == 1) never force the
  filtered program or the tp>1 fallback, and a drain that faults
  reaches the step guard with the just-issued launch parked in
  ``_pend`` (no orphaned in-flight launch).
"""

import jax
import numpy as np
import pytest

from triton_distributed_tpu.megakernel.ring import (
    RING_ADMIT,
    RING_CANCEL,
    RING_RETIRE,
    WorkRing,
)
from triton_distributed_tpu.models import AutoLLM
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.obs import kernel_trace as kt


@pytest.fixture
def ctx1():
    from triton_distributed_tpu.runtime import mesh as mesh_mod

    ctx = mesh_mod.initialize_distributed(tp=1, devices=jax.devices()[:1])
    yield ctx
    mesh_mod.finalize_distributed()


# -- host-side units (no model) -----------------------------------------


def test_work_ring_semantics():
    """The round protocol: push N items, publish bumps the doorbell and
    snapshots [doorbell, head, tail, occupancy], consume drains oldest
    first with monotonic seqs; overflow raises instead of dropping."""
    ring = WorkRing(capacity=4)
    ring.push(RING_ADMIT, 0, 12)
    ring.push(RING_RETIRE, 1, 7)
    ring.push(RING_CANCEL, 2)
    snap = ring.publish()
    assert snap.dtype == np.int32
    assert snap.tolist() == [1, 0, 3, 3]
    items = ring.consume()
    assert [(i.kind, i.slot, i.arg) for i in items] == [
        (RING_ADMIT, 0, 12), (RING_RETIRE, 1, 7), (RING_CANCEL, 2, 0),
    ]
    assert [i.seq for i in items] == [0, 1, 2]
    assert ring.occupancy == 0 and ring.peak_occupancy == 3
    # Empty round: the doorbell still advances (the kernel must be able
    # to tell "round with no work" from "no round").
    assert ring.publish().tolist() == [2, 3, 3, 0]
    # Wrap past capacity, then overflow loudly.
    for n in range(4):
        ring.push(RING_ADMIT, n)
    with pytest.raises(RuntimeError, match="work ring full"):
        ring.push(RING_ADMIT, 9)
    ring.publish()
    assert [i.slot for i in ring.consume()] == [0, 1, 2, 3]


def test_work_ring_publish_snapshot_and_flush():
    """``consume`` drains exactly up to the last publish's tail
    snapshot — items pushed after the doorbell stay host-owned for the
    next round — and ``flush`` drains everything without moving the
    doorbell (the single-step-fallback path)."""
    ring = WorkRing(capacity=4)
    ring.push(RING_ADMIT, 0)
    ring.publish()
    ring.push(RING_RETIRE, 1)  # after the publish: the NEXT round's
    items = ring.consume()
    assert [(i.kind, i.slot) for i in items] == [(RING_ADMIT, 0)]
    assert ring.occupancy == 1  # the unpublished item is still queued
    ring.publish()
    assert [i.slot for i in ring.consume()] == [1]
    # Nothing published since the drain: consume is empty even with
    # items queued; flush takes them all, doorbell untouched.
    ring.push(RING_CANCEL, 2)
    ring.push(RING_ADMIT, 3)
    assert ring.consume() == []
    bell = ring.doorbell
    flushed = ring.flush()
    assert [i.slot for i in flushed] == [2, 3]
    assert ring.occupancy == 0 and ring.doorbell == bell
    assert ring.flush() == []


def _rec(index, opcode, begin, end, mid=0, task_id=None):
    return kt.TaskRecord(0, 0, index, task_id or index, opcode, 0, 0,
                         begin, end, mid)


def test_validate_ring_doorbell_gap_check():
    """RING_POLL's mid column carries the OBSERVED doorbell, not a
    clock tick: it is exempt from the mid-in-interval check, and with
    ``doorbell=`` it must equal the published value exactly."""
    from triton_distributed_tpu.megakernel.task import TaskType

    poll = int(TaskType.RING_POLL)
    other = int(TaskType.LM_HEAD)
    records = [
        _rec(0, poll, 10, 20, mid=7),       # mid=doorbell, outside clock
        _rec(1, other, 20, 40, mid=30),
    ]
    assert kt.validate_ring(records) == []
    assert kt.validate_ring(records, doorbell=7) == []
    problems = kt.validate_ring(records, doorbell=8)
    assert len(problems) == 1 and "stale ring snapshot" in problems[0]
    # A non-poll record's mid stays clock-checked.
    bad = [_rec(0, other, 10, 20, mid=99)]
    assert any("outside" in p for p in kt.validate_ring(bad))
    # overlap_report summarizes the polls and their doorbell range.
    rep = kt.overlap_report(records)
    assert rep["ring_polls"] == 1
    assert rep["ring_doorbell_min"] == rep["ring_doorbell_max"] == 7


def test_cli_refusals_carry_ring_splice_reason(capsys):
    """Both CLIs still refuse --speculative × --mode mega as an
    argparse error (exit 2, before any model load), and the message now
    explains the RESIDENT reason: the work ring splices whole slots
    between rounds, never a mid-launch verify/rollback."""
    from perf import serve_demo
    from triton_distributed_tpu.serving import run_server

    for main in (run_server.main, serve_demo.main):
        with pytest.raises(SystemExit) as exc:
            main(["--speculative", "2", "--mode", "mega"])
        assert exc.value.code == 2
        err = capsys.readouterr().err
        assert "--speculative and --mode mega" in err
        assert "work ring splices whole slots" in err


def test_resident_knob_validation(capsys, ctx1):
    """--resident without --mode mega refuses by flag name at the CLI
    (exit 2, nothing loaded); the engine ctor enforces the same pair."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.serving import run_server

    with pytest.raises(SystemExit) as exc:
        run_server.main(["--resident", "--mode", "xla"])
    assert exc.value.code == 2
    assert "--resident requires --mode mega" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        run_server.main(["--ns", "0"])

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    with pytest.raises(ValueError, match="resident"):
        ContinuousEngine(model, max_batch=1, max_length=64,
                         mode="xla", resident=True)
    with pytest.raises(ValueError, match="ns"):
        ContinuousEngine(model, max_batch=1, max_length=64,
                         mode="mega", ns=0)


def test_ring_metrics_pretouch(fresh_telemetry, ctx1):
    """Engine construction alone pre-touches the resident-decode
    catalog: every new series reads 0 from the first scrape (PR 15
    convention), including the fallback counter the acceptance gate
    watches."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.obs import metrics as obs_metrics

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    ContinuousEngine(model, max_batch=1, page_size=16, max_length=64,
                     mode="mega")
    text = obs_metrics.prometheus_text()
    for name in (
        "tdt_mega_single_step_fallbacks_total",
        "tdt_mega_ring_items_total",
        "tdt_mega_ring_doorbells_total",
        "tdt_mega_ring_host_drains_total",
        "tdt_mega_device_retires_total",
        "tdt_mega_resident_rounds_total",
        "tdt_mega_bucket_launches_total",
        "tdt_mega_filtered_rounds_total",
    ):
        assert f"{name} 0" in text, name


# -- engine paths (tiny model, CPU interpret) ---------------------------


@pytest.mark.slow
def test_device_stop_retire_no_host_round_trip(ctx1):
    """A slot hitting eos mid-multi-step retires off the DEVICE stop
    test (mega_device_retires, not a host-side trim of a full launch),
    its pages flow back through the normal teardown (pool audit clean,
    radix tree receives the finished chain for reuse), and the
    co-batched survivor's tokens are bit-exact."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    p0 = np.asarray([5, 9, 2, 4], np.int32)
    p1 = np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32)
    probe = Engine(model, temperature=0.0).serve(p0[None], gen_len=6)[0, 4:]
    gold1 = Engine(model, temperature=0.0).serve(p1[None], gen_len=6)[0, 8:]
    eos = int(probe[1])  # p0 retires at its 2nd generated token

    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, eos_id=eos,
        mode="mega", prefix_cache=True,
    )
    free0 = len(eng.pool.free)
    outs = eng.run([(p0, 6), (p1, 6)])
    st = eng.stats
    assert st["mega_device_retires"] >= 1, st
    np.testing.assert_array_equal(outs[0], probe[:2])
    gold1_trim = gold1[: np.argmax(gold1 == eos) + 1] \
        if eos in gold1.tolist() else gold1
    np.testing.assert_array_equal(outs[1], np.asarray(gold1_trim))
    # Pages audit clean and back in the free list ∪ radix tree.
    assert eng.audit() == []
    # The retired chain landed in the radix tree: a re-run of the same
    # prompt + generated chain matches cached pages.
    chain = np.concatenate([p0, outs[0]])
    m = eng.prefix.match(chain)
    assert m.matched_len > 0
    eng.prefix.release_match(m)
    assert free0 == len(eng.pool.free) + eng.prefix.reclaimable_pages()


@pytest.mark.slow
def test_bucket_launch_bit_exact(ctx1):
    """2 live slots in a max_batch=4 engine ride a 2-wide bucket
    program (mega_bucket_launches) and emit exactly the tokens the
    full-width program emits — which themselves match the unfused
    goldens."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    prompts = [np.asarray([5, 9, 2, 4], np.int32),
               np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32)]
    gens = [5, 3]
    golds = [
        Engine(model, temperature=0.0).serve(p[None], gen_len=g)[0, len(p):]
        for p, g in zip(prompts, gens)
    ]

    def run(buckets):
        eng = ContinuousEngine(
            model, max_batch=4, page_size=16, max_length=64,
            mode="mega", mega_buckets=buckets,
        )
        outs = eng.run(list(zip(prompts, gens)))
        return outs, eng.stats

    outs_full, st_full = run(False)
    outs_b, st_b = run(True)
    assert st_full["mega_bucket_launches"] == 0
    assert st_b["mega_bucket_launches"] > 0, st_b
    for a, b, gold in zip(outs_full, outs_b, golds):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(b, np.asarray(gold))


@pytest.mark.slow
def test_resident_pipeline_ring_gap_free(ctx1):
    """Resident decode: round i+1 issues off round i's device outputs
    (mega_resident_rounds), admit/retire items flow through the work
    ring, every traced launch's ring validates gap-free against the
    doorbell the host published for it, and tokens stay bit-exact."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    prompts = [np.asarray([5, 9, 2, 4], np.int32),
               np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32)]
    golds = [
        Engine(model, temperature=0.0).serve(p[None], gen_len=6)[0, len(p):]
        for p in prompts
    ]
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, mode="mega",
        resident=True, kernel_trace=True, ns=2,
    )
    outs = eng.run([(p, 6) for p in prompts])
    for got, gold in zip(outs, golds):
        np.testing.assert_array_equal(got, np.asarray(gold))
    st = eng.stats
    assert st["mega_resident_rounds"] > 0, st
    assert st["mega_ring_items"] >= 4, st       # 2 admits + 2 retires
    assert st["mega_ring_doorbells"] > 0, st
    launches = eng.kernel_trace_launches()
    assert launches
    belled = 0
    for ln in launches:
        assert kt.validate_ring(ln.get_records(), doorbell=ln.doorbell) == []
        belled += ln.doorbell is not None
    assert belled > 0
    # Doorbells climb monotonically across the resident session.
    bells = [ln.doorbell for ln in launches if ln.doorbell is not None]
    assert bells == sorted(bells) and len(set(bells)) == len(bells)


@pytest.mark.slow
def test_sampled_run_scrapes_zero_fallbacks(fresh_telemetry, ctx1):
    """The acceptance gate: a PURE SAMPLED workload (every slot top-k +
    top-p) serves entirely through the in-kernel bisection filter —
    ``tdt_mega_single_step_fallbacks_total`` scrapes 0 and the filtered
    counter shows the rounds that previously fell back."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.obs import metrics as obs_metrics

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    prompts = [np.asarray([5, 9, 2, 4], np.int32),
               np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32)]
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, mode="mega",
        temperature=0.8, top_k=5, top_p=0.9, seed=3,
    )
    outs = eng.run([(p, 6) for p in prompts])
    assert all(len(o) == 6 for o in outs)
    st = eng.stats
    assert st["mega_filtered_rounds"] > 0, st
    assert st["mega_fallback_steps"] == 0, st
    reg = obs_metrics.default_registry()
    assert reg.get("tdt_mega_single_step_fallbacks_total").value() == 0
    assert reg.get("tdt_mega_filtered_rounds_total").value() > 0
    assert "tdt_mega_single_step_fallbacks_total 0" in \
        obs_metrics.prometheus_text()


@pytest.mark.slow
def test_persistent_fallback_drains_ring(ctx1):
    """A resident session whose every round falls back to single-step
    (ns=1 + filtered sampling can never compose a fused launch) must
    drain the work ring host-side: before the fix the admit/retire
    items were only consumed inside ``_launch_mega``, so a workload
    that persistently fell back overflowed the ring after ``capacity``
    items and the RuntimeError wedged every subsequent round."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    eng = ContinuousEngine(
        model, max_batch=1, page_size=16, max_length=64, mode="mega",
        resident=True, ns=1, temperature=0.8, top_k=5, top_p=0.9, seed=3,
    )
    # 4 requests push 4 admits + 4 retires: twice the shrunken
    # capacity, so any round that fails to drain overflows quickly.
    eng._ring = WorkRing(capacity=4)
    prompt = np.asarray([5, 9, 2, 4], np.int32)
    results = eng.run([(prompt, 4)] * 4, results=True)
    assert all(r.ok for r in results), [r.status for r in results]
    assert all(len(r.tokens) == 4 for r in results)
    st = eng.stats
    assert st["mega_fallback_steps"] > 0, st
    assert st["mega_ring_items"] == 8, st
    assert st["mega_ring_host_drains"] == 8, st
    assert st["mega_ring_doorbells"] == 0, st  # no fused launch ever
    assert eng._ring.occupancy == 0  # empty at rest after teardown
    assert eng.audit() == []


@pytest.mark.slow
def test_noop_filter_knobs_stay_fused(ctx1):
    """top_k >= vocab_size with top_p == 1 is a NO-OP filter: the plan
    gate must agree with the per-row enable (0 < k < V or p < 1) and
    compose the plain sampled launch — no filtered program at tp == 1
    (and no permanent single-step fallback at tp > 1). Tokens are
    bit-identical to the unfiltered sampled engine at the same seed."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    V = model.cfg.vocab_size
    prompts = [np.asarray([5, 9, 2, 4], np.int32),
               np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32)]

    def run(top_k, top_p):
        eng = ContinuousEngine(
            model, max_batch=2, page_size=16, max_length=64, mode="mega",
            temperature=0.8, top_k=top_k, top_p=top_p, seed=3,
        )
        return eng.run([(p, 6) for p in prompts]), eng.stats

    outs_noop, st = run(top_k=V, top_p=1.0)
    assert st["mega_filtered_rounds"] == 0, st
    assert st["mega_fallback_steps"] == 0, st
    outs_plain, _ = run(top_k=0, top_p=1.0)
    for a, b in zip(outs_noop, outs_plain):
        np.testing.assert_array_equal(a, b)


@pytest.mark.slow
def test_resident_drain_fault_parks_inflight_launch(ctx1):
    """A drain that raises mid-resident-round must reach the step guard
    with the just-issued NEXT launch already parked in ``_pend`` — so
    ``_abort_pend`` blocks on it before teardown frees pages it still
    reads (the pre-fix ordering drained first and orphaned the launch).
    The engine stays reusable and bit-exact afterwards."""
    from triton_distributed_tpu.models.continuous import ContinuousEngine
    from triton_distributed_tpu.runtime.faults import FaultPlan

    model = AutoLLM.from_pretrained("tiny", ctx=ctx1)
    prompts = [np.asarray([5, 9, 2, 4], np.int32),
               np.asarray([7, 1, 3, 8, 6, 2, 4, 9], np.int32)]
    golds = [
        Engine(model, temperature=0.0).serve(p[None], gen_len=6)[0, len(p):]
        for p in prompts
    ]
    eng = ContinuousEngine(
        model, max_batch=2, page_size=16, max_length=64, mode="mega",
        resident=True, ns=2,
    )
    # Spy on the drain entry: on pipelined rounds the next launch must
    # already be owned by ``_pend`` when the (possibly raising) drain
    # begins.
    parked, orig = [], eng._drain_launch
    eng._drain_launch = lambda pend: (
        parked.append(eng._pend is not None), orig(pend)
    )[1]
    with FaultPlan().on("engine.mega_drain", at=1):
        results = eng.run([(p, 6) for p in prompts], results=True)
    assert parked and parked[0], parked
    assert all(r.status == "failed" for r in results)
    assert all("injected" in r.reason for r in results)
    assert eng._pend is None  # the guard's _abort_pend reclaimed it
    assert eng.last_stats["decode_faults"] == 1
    assert eng.audit() == []
    eng._drain_launch = orig
    outs = eng.run([(p, 6) for p in prompts])
    for got, gold in zip(outs, golds):
        np.testing.assert_array_equal(got, np.asarray(gold))
